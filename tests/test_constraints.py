"""The model-generic constraint compiler (jepsen_tpu/analyze/
constraints.py).

The verdict-identity acceptance: a 280-history differential fuzz —
queue (unordered + FIFO), lock, and event-level multiset histories —
through the constraint prepass vs the engines / the basic multiset
checkers on every route, audit on.  Plus the decide-fast certificates
(W007/W008) validated and tamper-tested, the streamed total-queue fold
route (the seeded replicated-queue acceptance scenario, synthetic),
batch disposal + explain_batch mirroring, and the must-order prune.
"""

import os
import random
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from jepsen_tpu import synth  # noqa: E402
from jepsen_tpu.analyze.audit import audit, audit_events  # noqa: E402
from jepsen_tpu.analyze.constraints import (  # noqa: E402
    MultisetFold,
    analyze_constraints,
    analyze_prepass,
    analyze_queue_events,
    analyze_set_events,
    family_of,
)
from jepsen_tpu.checker import basic  # noqa: E402
from jepsen_tpu.checker.linear import check_opseq_linear  # noqa: E402
from jepsen_tpu.checker.seq import check_opseq  # noqa: E402
from jepsen_tpu.history import (  # noqa: E402
    Op,
    encode_ops,
    info_op,
    invoke_op,
    ok_op,
)
from jepsen_tpu.models import (  # noqa: E402
    fifo_queue,
    mutex,
    register,
    unordered_queue,
)


def ops(*specs):
    mk = {"invoke": invoke_op, "ok": ok_op, "info": info_op}
    return [mk[t](p, f, v) for t, p, f, v in specs]


def _queue_history(i: int, *, fifo: bool):
    rng = random.Random(9000 + i)
    h = synth.sim_queue_history(rng, 26, 4,
                                crash_p=rng.choice([0.0, 0.0, 0.2]),
                                fifo=fifo)
    if rng.random() < 0.5:
        h = (synth.swap_dequeues if rng.random() < 0.5
             else synth.corrupt_dequeue)(rng, h)
    return h


# ---------------------------------------------------------------------------
# differential fuzz: queue + lock OpSeq histories through every route
# ---------------------------------------------------------------------------


def test_queue_differential_fuzz_all_routes():
    """120 queue histories: the prepass-decided verdict must equal the
    prepass-off engine's, the prepass-on engines must stay
    verdict-identical, and every decided certificate must audit clean
    (maybe_audit raises inside the engines with audit=True)."""
    decided = 0
    for i in range(120):
        fifo = i % 2 == 1
        model = (fifo_queue if fifo else unordered_queue)(33)
        h = _queue_history(i, fifo=fifo)
        s = encode_ops(h, model.f_codes)
        ref = check_opseq(s, model, hb=False, lint=False,
                          max_configs=200_000)
        a = analyze_constraints(s, model)
        if a.decided is not None:
            decided += 1
            assert a.decided["valid"] == ref["valid"], \
                (i, a.stats, ref["valid"])
            au = audit(s, model, a.decided)
            assert au["ok"], (i, [str(d) for d in au["diagnostics"]])
        r = check_opseq(s, model, lint=False, max_configs=200_000,
                        audit=True)
        if ref["valid"] != "unknown" and r["valid"] != "unknown":
            assert r["valid"] == ref["valid"], i
        if i % 6 == 0:
            r2 = check_opseq_linear(s, model, lint=False,
                                    max_configs=200_000, audit=True,
                                    witness_cap=100_000)
            if ref["valid"] != "unknown" and r2["valid"] != "unknown":
                assert r2["valid"] == ref["valid"], i
    # the class this compiler exists for actually decides
    assert decided >= 30


def test_mutex_differential_fuzz():
    for i in range(60):
        rng = random.Random(5000 + i)
        model = mutex()
        h = synth.sim_mutex_history(rng, 22, 4,
                                    crash_p=rng.choice([0.0, 0.0, 0.2]))
        if rng.random() < 0.5:
            h = synth.mutate(rng, h)
        s = encode_ops(h, model.f_codes)
        ref = check_opseq(s, model, hb=False, lint=False,
                          max_configs=200_000)
        a = analyze_constraints(s, model)
        if a.decided is not None:
            assert a.decided["valid"] == ref["valid"], (i, a.stats)
            assert audit(s, model, a.decided)["ok"], i
        r = check_opseq(s, model, lint=False, max_configs=200_000,
                        audit=True)
        if ref["valid"] != "unknown" and r["valid"] != "unknown":
            assert r["valid"] == ref["valid"], i


def test_multiset_event_differential():
    """100 event-level histories: analyze_queue_events must agree with
    total_queue exactly, and its evidence must audit (W007)."""
    for i in range(100):
        h = _queue_history(1000 + i, fifo=False)
        post = basic.total_queue().check({}, h)
        ca = analyze_queue_events(h)
        assert ca["valid"] == post["valid"], i
        if ca["valid"] is False:
            assert ca["evidence"] is not None, i
            a = audit_events(h, {"valid": False,
                                 "queue_evidence": ca["evidence"]})
            assert a["ok"], (i, [str(d) for d in a["diagnostics"]])


def test_set_event_differential():
    rng = random.Random(3)
    for i in range(24):
        rng = random.Random(400 + i)
        n = rng.randrange(4, 16)
        adds = list(range(n))
        h = []
        seen = []
        for v in adds:
            h.append(invoke_op(0, "add", v))
            if rng.random() < 0.15:
                h.append(info_op(0, "add", v))
                if rng.random() < 0.5:
                    seen.append(v)
            else:
                h.append(ok_op(0, "add", v))
                seen.append(v)
        if rng.random() < 0.4 and seen:
            seen.remove(rng.choice(seen))  # lose one
        if rng.random() < 0.3:
            seen.append(999)  # unexpected member
        h.append(invoke_op(1, "read", None))
        h.append(ok_op(1, "read", list(seen)))
        post = basic.set_checker().check({}, h)
        ca = analyze_set_events(h)
        assert ca["valid"] == post["valid"], i
        if ca["valid"] is False:
            a = audit_events(h, {"valid": False,
                                 "queue_evidence": ca["evidence"]})
            assert a["ok"], (i, [str(d) for d in a["diagnostics"]])


# ---------------------------------------------------------------------------
# decide-fast certificates
# ---------------------------------------------------------------------------


def test_duplicate_delivery_decided_with_w008_certificate():
    model = unordered_queue(8)
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] == "duplicate-delivery"
    assert "queue_dup" in a.decided
    au = audit(s, model, a.decided)
    assert au["ok"] and au["checked"] == "queue_order"
    assert check_opseq(s, model, hb=False)["valid"] is False
    assert check_opseq(s, model)["engine"] == "constraint-decide"


def test_fifo_inversion_decided_with_w008_certificate():
    model = fifo_queue(8)
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] == "fifo-inversion"
    cyc = a.decided["queue_cycle"]
    assert [e["kind"] for e in cyc] == ["fifo", "rt"]
    for i, e in enumerate(cyc):
        assert e["dst"] == cyc[(i + 1) % len(cyc)]["src"]
    au = audit(s, model, a.decided)
    assert au["ok"], [str(d) for d in au["diagnostics"]]
    assert check_opseq(s, model, hb=False)["valid"] is False


def test_impossible_dequeue_decided_with_w007_certificate():
    model = unordered_queue(8)
    h = ops(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 7))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] == "impossible-dequeue"
    au = audit(s, model, a.decided)
    assert au["ok"] and au["checked"] == "queue_evidence"
    assert check_opseq(s, model, hb=False)["valid"] is False


def test_rf_cycle_decided():
    model = unordered_queue(8)
    # dequeue returns 1 and completes BEFORE the only enqueue of 1
    # invokes: the read-from edge closes a cycle with real time
    h = ops(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] in ("rf-cycle", "duplicate-delivery")
    assert audit(s, model, a.decided)["ok"]
    assert check_opseq(s, model, hb=False)["valid"] is False


def test_decide_valid_constructive_witness():
    model = unordered_queue(33)
    rng = random.Random(11)
    h = synth.sim_queue_history(rng, 24, 4, crash_p=0.0)
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is True
    assert a.stats["reason"] == "completion-schedule"
    au = audit(s, model, a.decided)
    assert au["ok"] and au["checked"] == "linearization"
    r = check_opseq(s, model)
    assert r["valid"] is True and r["configs"] == 0
    assert r["engine"] == "constraint-decide"


def test_lock_overhold_decided():
    model = mutex()
    h = ops(("invoke", 0, "acquire", None), ("ok", 0, "acquire", None),
            ("invoke", 1, "acquire", None), ("ok", 1, "acquire", None))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] == "lock-overhold"
    assert audit(s, model, a.decided)["ok"]
    assert check_opseq(s, model, hb=False)["valid"] is False


def test_lock_release_unheld_decided():
    model = mutex()
    h = ops(("invoke", 0, "release", None), ("ok", 0, "release", None))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    assert a.decided is not None and a.decided["valid"] is False
    assert a.stats["reason"] == "release-unheld"
    assert check_opseq(s, model, hb=False)["valid"] is False


def test_nonempty_init_state_is_out_of_scope():
    """A segment fold's carried state seeds the queue/lock: the
    empty-start algebra must cede rather than mis-decide."""
    from dataclasses import replace as _r

    from jepsen_tpu.models import Q_EMPTY

    model = unordered_queue(4)
    seeded = _r(model, init=(5, Q_EMPTY, Q_EMPTY, Q_EMPTY))
    h = ops(("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 5))
    s = encode_ops(h, seeded.f_codes)
    a = analyze_constraints(s, seeded)
    assert not a.applies and a.decided is None
    # and the engine (with the prepass on) gets the right answer
    assert check_opseq(s, seeded)["valid"] is True
    locked = _r(mutex(), init=(1,))
    h2 = ops(("invoke", 0, "release", None), ("ok", 0, "release", None))
    s2 = encode_ops(h2, locked.f_codes)
    a2 = analyze_constraints(s2, locked)
    assert not a2.applies
    assert check_opseq(s2, locked)["valid"] is True


# ---------------------------------------------------------------------------
# tamper tests: W007 / W008
# ---------------------------------------------------------------------------


def test_w008_tampered_dup_certificate():
    model = unordered_queue(8)
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    cert = dict(a.decided)
    # drop a dequeue row: the set is no longer complete
    cert["queue_dup"] = {"dequeues": cert["queue_dup"]["dequeues"][:1],
                         "enqueues": cert["queue_dup"]["enqueues"]}
    au = audit(s, model, cert)
    assert not au["ok"] and "W008" in au["codes"]
    # out-of-range row -> W001
    cert2 = dict(a.decided)
    cert2["queue_dup"] = {"dequeues": [99], "enqueues": []}
    au2 = audit(s, model, cert2)
    assert not au2["ok"] and "W001" in au2["codes"]


def test_w008_tampered_fifo_certificate():
    model = fifo_queue(8)
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 2),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    cyc = [dict(e) for e in a.decided["queue_cycle"]]
    # swap the via pair: the enqueue order no longer justifies FIFO
    fifo_edge = next(e for e in cyc if e["kind"] == "fifo")
    fifo_edge["via"] = list(reversed(fifo_edge["via"]))
    au = audit(s, model, {"valid": False, "queue_cycle": cyc})
    assert not au["ok"] and "W008" in au["codes"]
    # break the chain
    cyc2 = [dict(e) for e in a.decided["queue_cycle"]]
    cyc2[0]["dst"] = cyc2[0]["src"]
    au2 = audit(s, model, {"valid": False, "queue_cycle": cyc2})
    assert not au2["ok"] and "W008" in au2["codes"]


def test_w007_tampered_event_evidence():
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 1),
            ("invoke", 0, "enqueue", 2), ("ok", 0, "enqueue", 2),
            ("invoke", 2, "drain", None), ("ok", 2, "drain", []))
    # value 2 is genuinely lost; claim value 1's enqueue instead
    bad = {"valid": False,
           "queue_evidence": {"family": "queue",
                              "kind": "lost-acked-enqueue",
                              "rows": [1], "values": ["1"]}}
    a = audit_events(h, bad)
    assert not a["ok"] and "W007" in a["codes"]
    good = {"valid": False,
            "queue_evidence": {"family": "queue",
                               "kind": "lost-acked-enqueue",
                               "rows": [5], "values": ["2"]}}
    assert audit_events(h, good)["ok"]
    # wrong kind on the same rows
    wrong = {"valid": False,
             "queue_evidence": {"family": "queue",
                                "kind": "unexpected-dequeue",
                                "rows": [5]}}
    assert not audit_events(h, wrong)["ok"]


def test_w007_tampered_opseq_evidence():
    model = unordered_queue(8)
    h = ops(("invoke", 0, "enqueue", 3), ("ok", 0, "enqueue", 3),
            ("invoke", 1, "dequeue", None), ("ok", 1, "dequeue", 7))
    s = encode_ops(h, model.f_codes)
    a = analyze_constraints(s, model)
    cert = dict(a.decided)
    # point the evidence at the legal enqueue row instead
    cert["queue_evidence"] = {"family": "queue",
                              "kind": "unexpected-dequeue", "rows": [0]}
    del cert["final_ops"]
    au = audit(s, model, cert)
    assert not au["ok"] and "W007" in au["codes"]


# ---------------------------------------------------------------------------
# the prune + batch disposal mirror
# ---------------------------------------------------------------------------


def test_undecided_queue_emits_rf_edges_and_stays_identical():
    model = unordered_queue(33)
    rng = random.Random(21)
    # crashes push the history out of the decide-valid class but keep
    # the rf edges: the engines must agree under the mask
    for i in range(12):
        rng = random.Random(600 + i)
        h = synth.sim_queue_history(rng, 24, 4, crash_p=0.3)
        s = encode_ops(h, model.f_codes)
        a = analyze_constraints(s, model)
        if a.decided is not None:
            continue
        ref = check_opseq(s, model, hb=False, lint=False,
                          max_configs=200_000)
        r = check_opseq(s, model, lint=False, max_configs=200_000)
        if "unknown" not in (ref["valid"], r["valid"]):
            assert r["valid"] == ref["valid"], i
        if a.must_pred:
            assert a.stats["must_edges"] > 0


def test_batch_disposal_and_explain_batch_mirror():
    from jepsen_tpu.analyze.plan import explain_batch
    from jepsen_tpu.checker.linearizable import search_batch

    model = unordered_queue(33)
    seqs = []
    for i in range(6):
        rng = random.Random(700 + i)
        h = synth.sim_queue_history(rng, 20, 4, crash_p=0.0)
        if i % 2:
            h = synth.corrupt_dequeue(rng, h)
        seqs.append(encode_ops(h, model.f_codes))
    rs = search_batch(seqs, model, bucket=True, budget=100_000,
                      lint=False)
    n_cd = sum(1 for r in rs if r.get("engine") == "constraint-decide")
    assert n_cd >= 1
    stats = rs[0].get("bucket_batch")
    plan = explain_batch(seqs, model)
    assert plan["constraint_decided"] == n_cd if stats is None else True
    if stats is not None:
        assert stats["constraint_decided"] == \
            plan["constraint_decided"]
        assert stats["hb_decided"] == plan["hb_decided"] == 0


def test_explain_constraints_block():
    from jepsen_tpu.analyze.plan import explain, render_plan

    model = unordered_queue(33)
    rng = random.Random(31)
    h = synth.sim_queue_history(rng, 20, 4)
    plan = explain(h, model)
    cs = plan["constraints"]
    assert cs["applies"] and cs["family"] == "queue"
    assert cs["stream_fold"] == {"eligible": True,
                                 "route": "total-queue"}
    assert "constraints[queue]" in render_plan(plan)
    # register models keep the hb block and an explicit n/a here
    rplan = explain(synth.sim_register_history(random.Random(1),
                                               cas=False),
                    register(0))
    assert rplan["constraints"]["applies"] is False


# ---------------------------------------------------------------------------
# the streamed total-queue fold route
# ---------------------------------------------------------------------------


def _feed(sink, hist, op):
    hist.append(op)
    sink.ingest(op)


def test_total_fold_stream_unexpected_flips_mid_stream():
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("total-queue")
    hist = []
    _feed(sink, hist, invoke_op(0, "enqueue", 1))
    _feed(sink, hist, ok_op(0, "enqueue", 1))
    _feed(sink, hist, invoke_op(1, "dequeue", None))
    _feed(sink, hist, ok_op(1, "dequeue", 777))
    assert sink.verdict()["status"] == "invalid"
    flip_at = sink.verdict()["invalid_event"]
    _feed(sink, hist, invoke_op(1, "dequeue", None))
    _feed(sink, hist, ok_op(1, "dequeue", 1))
    final = sink.finalize(audit=True)
    assert final["valid"] is False
    assert final["stream"]["invalid_event"] == flip_at == 3
    assert final["queue_evidence"]["kind"] == "unexpected-dequeue"
    assert final["audit"]["ok"]
    # bit-identical to the post-hoc multiset checker
    assert basic.total_queue().check({}, hist)["valid"] is False


def test_total_fold_stream_valid_history_stays_valid():
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("total-queue")
    hist = []
    for j in range(8):
        _feed(sink, hist, invoke_op(0, "enqueue", j))
        _feed(sink, hist, ok_op(0, "enqueue", j))
    _feed(sink, hist, invoke_op(1, "drain", None))
    _feed(sink, hist, ok_op(1, "drain", list(range(8))))
    assert sink.verdict()["status"] == "valid-so-far"
    final = sink.finalize(audit=True)
    assert final["valid"] is True
    assert final["stream"]["invalid_event"] is None
    assert basic.total_queue().check({}, hist)["valid"] is True


def test_total_fold_stream_set_family():
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("set")
    hist = []
    for j in range(4):
        _feed(sink, hist, invoke_op(0, "add", j))
        _feed(sink, hist, ok_op(0, "add", j))
    _feed(sink, hist, invoke_op(1, "read", None))
    _feed(sink, hist, ok_op(1, "read", [0, 1, 3]))  # 2 lost
    assert sink.verdict()["status"] == "invalid"
    final = sink.finalize(audit=True)
    assert final["valid"] is False
    assert final["queue_evidence"]["kind"] == "lost-acked-add"
    assert final["audit"]["ok"]


def test_seeded_replicated_queue_cell_grades_streamed():
    """The acceptance scenario, synthetic: a bridge-election
    lost-acked-enqueue history (acked ADDJOBs missing from the final
    drain) through the fold sink + the campaign's detection grader —
    detection.at == "streamed" with recorded latency, final verdict
    bit-identical to the post-hoc multiset checker, W007 certificate
    passing analyze/audit.py."""
    from dataclasses import replace as _r

    from jepsen_tpu.live.campaign import _detection
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("total-queue")
    hist = []
    t = 0

    def tfeed(op):
        nonlocal t
        t += 100_000_000
        _feed(sink, hist, _r(op, time=t))

    for j in range(30):
        tfeed(invoke_op(j % 4, "enqueue", j))
        tfeed(ok_op(j % 4, "enqueue", j))
    # the bridge grudge lands (link partition nemesis journals :info)
    tfeed(info_op("nemesis", "start", None))
    tfeed(info_op("nemesis", "start", ["n1", "n2"]))
    # a cut-off replica wins the election; the final drain comes short
    tfeed(invoke_op(0, "drain", None))
    tfeed(ok_op(0, "drain", [j for j in range(30) if j not in (4, 9)]))
    final = sink.finalize(audit=True)
    post = basic.total_queue().check({}, [op for op in hist
                                          if isinstance(op.process,
                                                        int)])
    assert final["valid"] is False and post["valid"] is False
    assert sorted(post["lost"]) == [4, 9]
    assert final["queue_evidence"]["kind"] == "lost-acked-enqueue"
    assert final["audit"]["ok"]  # the W007 certificate passes audit
    test = {"history": hist, "stream_results": final, "results": post}
    det = _detection(test, "link-bridge")
    assert det["at"] == "streamed"
    assert det["fold"] == "total-queue"
    assert det["invalid_event"] == len(hist) - 1 - 0  # the drain event
    assert det["latency_events"] >= 0 and "latency_s" in det
    assert det["fault_event"] < det["invalid_event"]


def test_multiset_fold_lost_waits_for_drain_quiescence():
    fold = MultisetFold("total-queue")
    i = 0

    def step(op):
        nonlocal i
        out = fold.step(op, i)
        i += 1
        return out

    assert step(invoke_op(0, "enqueue", 1)) is None
    assert step(ok_op(0, "enqueue", 1)) is None
    # no drain yet: a missing value is NOT lost mid-run
    assert step(invoke_op(1, "enqueue", 2)) is None
    assert step(ok_op(1, "enqueue", 2)) is None
    assert step(invoke_op(0, "drain", None)) is None
    flip = step(ok_op(0, "drain", [1]))
    assert flip is not None and flip["kind"] == "lost-acked-enqueue"
    assert flip["values"] == ["2"]


def test_prepare_test_installs_fold_sink():
    from jepsen_tpu import core

    test = core.prepare_test({"stream": True,
                              "stream_fold": "total-queue"})
    sink = test.get("__stream_check__")
    assert sink is not None
    assert type(sink).__name__ == "TotalFoldStream"
    sink.close()
    # model-less with no fold route: post-hoc only, as before
    test2 = core.prepare_test({"stream": True})
    assert test2.get("__stream_check__") is None


def test_queue_backends_declare_fold_route():
    from jepsen_tpu.live.backend import FAMILIES

    for fam in ("queue", "replicated-queue"):
        w = FAMILIES[fam].workload({})
        assert w.get("stream_fold") == "total-queue", fam
        t = FAMILIES[fam].build_test({"data_root": "/tmp/x"})
        assert t.get("stream_fold") == "total-queue", fam


def test_family_dispatch():
    assert family_of(unordered_queue(8)) == "queue"
    assert family_of(fifo_queue(8)) == "fifo-queue"
    assert family_of(mutex()) == "lock"
    assert family_of(register(0)) is None
    # analyze_prepass routes registers to the HB solver
    rng = random.Random(2)
    h = synth.register_history(rng, n_ops=20, n_procs=3, cas=False,
                               unique_writes=True)
    s = encode_ops(h, register(0).f_codes)
    a = analyze_prepass(s, register(0))
    assert a.stats.get("solver") != "constraints"


def test_multiset_fold_no_false_flip_after_drain():
    """An enqueue acked AFTER a drain must not be flagged lost at its
    own completion (the lost rule runs only AT drain events)."""
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("total-queue")
    hist = []
    _feed(sink, hist, invoke_op(0, "drain", None))
    _feed(sink, hist, ok_op(0, "drain", []))
    _feed(sink, hist, invoke_op(1, "enqueue", 1))
    _feed(sink, hist, ok_op(1, "enqueue", 1))
    assert sink.verdict()["status"] != "invalid"
    _feed(sink, hist, invoke_op(1, "dequeue", None))
    _feed(sink, hist, ok_op(1, "dequeue", 1))
    final = sink.finalize(audit=True)
    assert final["valid"] is True
    assert basic.total_queue().check({}, hist)["valid"] is True
    # same for the set family: an add acked after the read is not lost
    sink2 = TotalFoldStream("set")
    h2 = []
    _feed(sink2, h2, invoke_op(0, "add", 1))
    _feed(sink2, h2, ok_op(0, "add", 1))
    _feed(sink2, h2, invoke_op(1, "read", None))
    _feed(sink2, h2, ok_op(1, "read", [1]))
    _feed(sink2, h2, invoke_op(0, "add", 2))
    _feed(sink2, h2, ok_op(0, "add", 2))
    assert sink2.verdict()["status"] != "invalid"


def test_total_fold_final_certificate_matches_final_verdict():
    """A stale provisional flip (a value a LATER drain delivered) must
    not leak into the final certificate: finalize recomputes the
    evidence against the whole history, and the W007 audit passes."""
    from jepsen_tpu.stream.checker import TotalFoldStream

    sink = TotalFoldStream("total-queue")
    hist = []
    _feed(sink, hist, invoke_op(0, "enqueue", 1))
    _feed(sink, hist, ok_op(0, "enqueue", 1))
    _feed(sink, hist, invoke_op(1, "enqueue", 2))
    _feed(sink, hist, ok_op(1, "enqueue", 2))
    # first drain comes up empty at a quiescent point: provisional
    # flip names BOTH values
    _feed(sink, hist, invoke_op(0, "drain", None))
    _feed(sink, hist, ok_op(0, "drain", []))
    assert sink.verdict()["status"] == "invalid"
    # a second drain delivers value 1: only value 2 is really lost
    _feed(sink, hist, invoke_op(1, "drain", None))
    _feed(sink, hist, ok_op(1, "drain", [1]))
    final = sink.finalize(audit=True)  # audit raises on a bad cert
    assert final["valid"] is False
    assert final["queue_evidence"]["values"] == ["2"]
    assert final["audit"]["ok"]


def test_w007_duplicate_payload_lost_uses_counts():
    """Multiset semantics: a payload enqueued :ok twice with one copy
    delivered is still lost — the audit must count, not set-check."""
    h = ops(("invoke", 0, "enqueue", 1), ("ok", 0, "enqueue", 1),
            ("invoke", 1, "enqueue", 1), ("ok", 1, "enqueue", 1),
            ("invoke", 2, "dequeue", None), ("ok", 2, "dequeue", 1),
            ("invoke", 0, "drain", None), ("ok", 0, "drain", []))
    post = basic.total_queue().check({}, h)
    ca = analyze_queue_events(h)
    assert post["valid"] is False and ca["valid"] is False
    a = audit_events(h, {"valid": False,
                         "queue_evidence": ca["evidence"]})
    assert a["ok"], [str(d) for d in a["diagnostics"]]
