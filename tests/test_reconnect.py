"""reconnect.Backoff — the capped-exponential-with-jitter schedule.

The satellite contract: reopen loops use capped exponential backoff
with jitter and a max-attempts budget instead of fixed-interval
retries, and the schedule itself is unit-tested (rng injectable, no
real sleeping anywhere in here).
"""

import random

import pytest

from jepsen_tpu.reconnect import Backoff, Wrapper


def test_raw_schedule_grows_then_caps():
    b = Backoff(base=0.05, cap=2.0, factor=2.0, max_attempts=10,
                jitter=0.0)
    raws = [b.raw_delay(i) for i in range(9)]
    # strictly growing until the cap, then flat at the cap
    assert raws[0] == pytest.approx(0.05)
    assert raws[1] == pytest.approx(0.10)
    for a, b_ in zip(raws, raws[1:]):
        assert b_ >= a
    assert raws[-1] == 2.0
    assert raws[-2] == 2.0  # capped before the end: 0.05*2^6 = 3.2 > 2


def test_jitter_shortens_but_never_inflates():
    b = Backoff(base=0.1, cap=5.0, factor=2.0, max_attempts=12,
                jitter=0.5, rng=random.Random(42))
    for i in range(11):
        d = b.delay(i)
        raw = b.raw_delay(i)
        assert 0.5 * raw <= d <= raw


def test_delays_budget_and_length():
    b = Backoff(base=0.05, cap=1.0, factor=2.0, max_attempts=6,
                jitter=0.0)
    ds = b.delays()
    # attempt 0 runs immediately: budget is max_attempts - 1 sleeps
    assert len(ds) == 5
    assert sum(ds) == pytest.approx(b.budget_s())
    assert b.budget_s() == pytest.approx(0.05 + 0.1 + 0.2 + 0.4 + 0.8)


def test_run_retries_until_success_with_scheduled_sleeps():
    b = Backoff(base=0.05, cap=2.0, factor=2.0, max_attempts=8,
                jitter=0.0)
    slept = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise OSError("not yet")
        return "up"

    assert b.run(flaky, sleep=slept.append) == "up"
    assert calls["n"] == 4
    assert slept == pytest.approx([0.05, 0.1, 0.2])


def test_run_exhausts_budget_and_reraises_last():
    b = Backoff(base=0.01, cap=0.02, max_attempts=3, jitter=0.0)
    slept = []

    def dead():
        raise ConnectionRefusedError("still down")

    with pytest.raises(ConnectionRefusedError):
        b.run(dead, sleep=slept.append)
    assert len(slept) == 2  # budget: 3 attempts = 2 sleeps


def test_wrapper_reopen_uses_backoff():
    """The reopen loop rides the schedule: a conn that fails twice then
    succeeds opens without raising, with the scheduled sleeps."""
    attempts = {"n": 0}

    def opener():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise OSError("refused")
        return f"conn{attempts['n']}"

    slept = []
    b = Backoff(base=0.05, cap=1.0, factor=2.0, max_attempts=5,
                jitter=0.0)
    b_run = b.run

    # spy on the sleeps without monkeypatching time.sleep globally
    def run_spy(fn, **kw):
        kw["sleep"] = slept.append
        return b_run(fn, **kw)

    b.run = run_spy
    w = Wrapper(open=opener, backoff=b, log_errors=False)
    assert w.conn() == "conn3"
    assert slept == pytest.approx([0.05, 0.1])
    # budget exhaustion propagates the last error out of open()
    attempts["n"] = -100
    w2 = Wrapper(open=opener,
                 backoff=Backoff(base=0.0, cap=0.0, max_attempts=2,
                                 jitter=0.0),
                 log_errors=False)
    with pytest.raises(OSError):
        w2.reopen()


def test_wrapper_without_backoff_single_attempt():
    attempts = {"n": 0}

    def opener():
        attempts["n"] += 1
        raise OSError("down")

    w = Wrapper(open=opener, log_errors=False)
    with pytest.raises(OSError):
        w.open()
    assert attempts["n"] == 1


# ---------------------------------------------------------------------------
# the stateful schedule: step()/exhausted()/reset() for health loops
# ---------------------------------------------------------------------------


def test_step_walks_the_schedule_and_reset_rearms():
    b = Backoff(base=0.05, cap=2.0, factor=2.0, max_attempts=6,
                jitter=0.0)
    first_run = [b.step() for _ in range(3)]
    assert first_run == [pytest.approx(0.05), pytest.approx(0.1),
                         pytest.approx(0.2)]
    # a successful health check resets: the next failure ramps from
    # the BASE delay again, not from where the last outage left off
    b.reset()
    assert b.step() == pytest.approx(0.05)


def test_step_pins_at_cap_past_the_schedule():
    b = Backoff(base=0.5, cap=1.0, factor=2.0, max_attempts=3,
                jitter=0.0)
    assert b.step() == pytest.approx(0.5)
    assert b.step() == pytest.approx(1.0)
    assert b.exhausted()
    # stepping an exhausted backoff stays pinned at the cap — a caller
    # that ignores exhausted() still never spins faster than the cap
    assert b.step() == pytest.approx(1.0)
    assert b.step() == pytest.approx(1.0)


def test_exhausted_flips_at_the_attempts_budget_and_reset_clears():
    b = Backoff(base=0.01, cap=0.1, factor=2.0, max_attempts=4,
                jitter=0.0)
    seen = 0
    while not b.exhausted():
        b.step()
        seen += 1
    assert seen == 3  # the sleeps budget: max_attempts - 1
    b.reset()
    assert not b.exhausted()


def test_process_db_health_loop_resets_on_success_and_fails_fast():
    """The live/backend.py wiring: one stateful Backoff per node —
    success resets it (a node that recovers then re-fails re-ramps
    from base), exhaustion makes the NEXT wait on a still-dead node
    fail after a single probe instead of re-paying the whole ramp."""
    from jepsen_tpu.live import backend as live_backend

    class FlakyBackend(live_backend.LiveBackend):
        name = "flaky"

        def __init__(self):
            self.healthy = False
            self.probes = 0

        def health_check(self, test, node):
            self.probes += 1
            if not self.healthy:
                raise OSError("still down")

    fb = FlakyBackend()
    db = live_backend.ProcessDB(
        fb, health_backoff=Backoff(base=0.001, cap=0.002, factor=2.0,
                                   max_attempts=3, jitter=0.0))
    test = {"nodes": ["n1"]}

    with pytest.raises(RuntimeError):
        db._health_wait(test, "n1")
    assert fb.probes == 3  # the full (tiny) budget
    # still dead: the node's backoff is exhausted, so the next wait
    # costs exactly ONE probe
    with pytest.raises(RuntimeError):
        db._health_wait(test, "n1")
    assert fb.probes == 4
    # the node comes back: one probe succeeds and RESETS the schedule
    fb.healthy = True
    db._health_wait(test, "n1")
    assert fb.probes == 5
    assert db._node_health["n1"].attempt == 0
    # it fails again later: the ramp starts over from base (a fresh
    # budget), not from the exhausted cursor
    fb.healthy = False
    with pytest.raises(RuntimeError):
        db._health_wait(test, "n1")
    assert fb.probes == 8
