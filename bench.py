"""Headline benchmark: time-to-verdict on the BASELINE.md configs.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

BASELINE.md's metric is "ops-verified/sec on a 10k-op CAS-register
history; speedup vs knossos on CPU".  Every tier here runs to a DECIDED
verdict (valid/invalid) wherever the deadline allows, and the headline
value is verified-ops/second on the 10k-op history: n_ops / seconds to
the device engine's decided verdict.

Comparators, strongest-first (all exact, all this repo's own — no JVM
exists in this image, so knossos itself cannot run here):

  * ``host16`` — checker/parallel.py portfolio: min(16, cpu_count)
    processes racing the `linear` sweep against WGL DFS variants under
    different exploration orders; first conclusive verdict wins.  The
    honest stand-in for "knossos.competition on a 16-core CPU"
    (BASELINE.json).  ``vs_baseline`` is host16_seconds /
    device_seconds and is reported ONLY when the portfolio actually had
    >= 8 cores — on smaller build hosts it is null and the single-core
    ratios live in the detail.
  * ``host_linear`` — the single-core `linear` algorithm
    (checker/linear.py), the repo's fastest host checker.

Labeling contract (round-2 lesson): ``backend`` is always the real JAX
backend the tier executed on; the engine name never claims "tpu" — a
CPU-fallback run is labeled exactly that, and the metric string reports
the n_ops actually verified.

Robustness contract: this script ALWAYS emits its JSON line.  The TPU
(axon PJRT plugin) can take minutes of wall clock on first backend
touch, hang forever when the tunnel is down, or KILL its worker if any
single execution outlives its ~60s watchdog — and a crashed worker
poisons the whole process's jax backend.  So:

  * the backend is probed in a subprocess while the host comparators
    run in the parent;
  * every device tier runs in its OWN subprocess (``--run-tier``) with a
    parent-side timeout: a worker crash costs one tier, not the bench,
    and the parent retries the tier on a pinned-CPU child;
  * tiers run smallest-first under a wall-clock budget, and
    SIGTERM/SIGALRM print the best completed tier before exiting.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

QUICK = "--quick" in sys.argv

# --trace: record flight-recorder spans (jepsen_tpu/obs) through every
# tier — the env var reaches the tier children, each of which dumps its
# Chrome trace to BENCH_trace_<tier>.json next to the numbers, so a
# bench regression comes with its own where-did-the-wall-go evidence
if "--trace" in sys.argv:
    os.environ["JEPSEN_TPU_TRACE"] = "1"

T0 = time.time()
# Total wall-clock budget for the whole script.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "300" if QUICK else "1100"))
# Backend probe budget: axon first touch has been observed to take ~9min
# when the tunnel is cold (and 2s when it is warm).
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "60" if QUICK else "300"))
# Host-comparator phase cap (runs concurrently with the backend probe).
HOST_S = float(os.environ.get("BENCH_HOST_S", "60" if QUICK else "240"))

#: (name, n_ops, n_procs, device config budget, headline, tier deadline s)
#: the 10k deadline covers a cold-cache CPU-fallback decide (~250s search
#: + compiles); on a warm TPU it finishes far earlier
#: batch256 runs BEFORE the 10k headline: the 10k is the longest search
#: and the one observed to wedge an open tunnel mid-run (r4) — a wedge
#: there must not cost the batch tier its only accelerator window
TIERS = [("1k", 1_000, 32, 5_000_000, False, 90.0),
         ("mutex2k", 2_000, 16, 30_000_000, False, 90.0),
         ("batch256", 128, 8, 2_000_000, False, 120.0),
         ("10k", 10_000, 32, 100_000_000, True, 420.0),
         # the ROADMAP's unique-writes wide tier: 10k ops, every write
         # a DISTINCT value, overlap kept permanently in flight (no
         # quiescent point) — the per-value block decomposition's
         # class at device-relevant scale, so config 5's
         # `applies: false` stops being the only decomposition data
         # point.  Corrupted by swapping two distant reads' values:
         # the block-ORDER invalidity mode the cross-block acyclicity
         # test exists for (a never-written value would be rejected
         # before any order reasoning).
         ("10kuniq", 10_000, 32, 100_000_000, False, 180.0),
         # BASELINE config #5's worst-case-frontier variant: 64
         # processes at overlap 32 force genuinely WIDE pruned levels —
         # the regime where the device's lockstep lanes should beat the
         # host outright.  Last (lowest priority); its search
         # checkpoints to .bench_ckpt, so undecided runs ACCUMULATE
         # toward a decided verdict across bench invocations.
         ("10k64", 10_000, 64, 200_000_000, False, 180.0)]

#: the ONE vs_baseline definition every tier row uses (VERDICT r4
#: weak #7: two unstated, different extrapolation bases made rows of
#: the same JSON incomparable).  Each row's vs_baseline_basis states
#: whether its 16-core model was measured or extrapolated, and how.
VS_BASELINE_CONVENTION = (
    "vs_baseline = modeled 16-core-host wall seconds / device wall "
    "seconds, same tier.  The 16-core model is MEASURED when this host "
    "has >= 8 cores (process portfolio for single histories, process "
    "pool for the batch tier); otherwise it is an extrapolation whose "
    "exact basis is stated in that row's vs_baseline_basis.")

_BEST: dict | None = None
#: priority of the tier behind _BEST: (headline-tier?, decided?,
#: n_ops) — lets a BENCH_TIER_ORDER subset without the 10k tier still
#: emit its best completed tier as the headline instead of the error
#: payload, and keeps an undecided rate tier from displacing a decided
#: verdict
_BEST_PRIO: tuple = (-1, -1, -1)
_BEST_TIER: str | None = None
_EXTRA: dict = {}
_EMITTED = False
_PROBE: "subprocess.Popen | None" = None
_CHILD: "subprocess.Popen | None" = None


def _resolve_nominal(name: str, gen, encode, target: int, *,
                     lo_guess: int):
    """Memoized front-end for :func:`_exact_encoded`: the scan is
    deterministic, so its resolved nominal-n is computed once and shared
    with every child/worker process through the environment (spawned
    comparator workers would otherwise each repeat a multi-second
    scan before signalling ready)."""
    key = f"BENCH_NOMINAL_{name}"
    if key in os.environ:
        n = int(os.environ[key])
        h = gen(n)
        return h, encode(h)
    h, seq, n = _exact_encoded(gen, encode, target, lo_guess=lo_guess)
    os.environ[key] = str(n)
    return h, seq


def _exact_encoded(gen, encode, target: int, *, lo_guess: int):
    """Scan the generator's nominal invoke count until the ENCODED row
    count equals ``target`` exactly (round-3 lesson: encode_ops drops
    :fail ops, so tier "1k" used to carry only 745 rows and the labels
    overstated the work).  ``gen(n)`` -> event history; ``encode(h)`` ->
    OpSeq.  Deterministic: the scan order is fixed, so every process
    rebuilds the identical history."""
    n = lo_guess
    best = None  # (abs gap, n, h, seq)
    seen: set[int] = set()
    for _ in range(200):
        h = gen(n)
        seq = encode(h)
        got = len(seq)
        if got == target:
            return h, seq, n
        if best is None or abs(got - target) < best[0]:
            best = (abs(got - target), n, h, seq)
        seen.add(n)
        # proportional step toward the target, at least +-1
        step = int(round(n * (target - got) / max(1, got)))
        n += step if step else (1 if got < target else -1)
        n = max(target // 2, n)
        if n in seen:
            # walk to the nearest unvisited candidate; give up once the
            # local neighborhood is exhausted (nearest-miss is honest —
            # the emitted n_ops is always the actual encoded count)
            for d in range(1, 50):
                if n + d not in seen:
                    n += d
                    break
                if n - d > target // 2 and n - d not in seen:
                    n -= d
                    break
            else:
                break
    return best[2], best[3], best[1]


_SEQ_CACHE: dict = {}


def make_seq(name: str):
    """Deterministic per-tier history (seeded by the tier name, so child
    and comparator processes rebuild the identical history).  The
    ENCODED op count equals the tier's nominal size exactly (labels must
    not overstate the verified work — VERDICT r3 weak #3)."""
    if name in _SEQ_CACHE:
        return _SEQ_CACHE[name]
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register, mutex, register
    from jepsen_tpu.synth import (corrupt_read, register_history,
                                  sim_mutex_history, swap_read_values)

    spec = {t[0]: t for t in TIERS}[name]
    _, n_ops, n_procs, _, _, _ = spec
    if name == "10kuniq":
        # unique-writes wide tier: no crashes/:fail ops and cas=False,
        # so the encoded count equals the invoke count exactly; the
        # distant-read swap makes the history (almost surely) invalid
        # through the forced block ORDER, the deep invalidity mode
        model = register(0)

        def gen(n):
            rng = random.Random(f"bench-{name}")
            h = register_history(rng, n_ops=n, n_procs=n_procs,
                                 overlap=8, crash_p=0.0, cas=False,
                                 unique_writes=True)
            return swap_read_values(rng, h)

        _, seq = _resolve_nominal(name, gen,
                                  lambda h: encode_ops(h, model.f_codes),
                                  n_ops, lo_guess=n_ops)
        _SEQ_CACHE[name] = (seq, model)
        return seq, model
    if name.startswith("mutex"):
        # BASELINE config #4: lock workload with nemesis-induced :info
        # (crashed) ops — the indeterminate-op stressor.  An acquire
        # chain is appended so the history is invalid NO MATTER how the
        # checker places the :info ops: each :info release can "unlock"
        # at most once, so (#info + 2) consecutive ok acquires cannot
        # all be explained.  (A valid history would be disposed of by
        # the O(n) greedy witness, as knossos's DFS would lucky-dive;
        # the tier must measure the sweep.)
        from jepsen_tpu.history import invoke_op, ok_op

        model = mutex()

        def gen(n):
            rng = random.Random(f"bench-{name}")
            h = sim_mutex_history(rng, n_ops=n, n_procs=n_procs,
                                  crash_p=0.01, max_crashes=12)
            n_info = sum(1 for op in h if op.type == "info")
            for i in range(n_info + 2):
                p = n_procs + i
                h = h + [invoke_op(p, "acquire", None),
                         ok_op(p, "acquire", None)]
            return h

        _, seq = _resolve_nominal(name, gen,
                                  lambda h: encode_ops(h, model.f_codes),
                                  n_ops, lo_guess=n_ops)
        _SEQ_CACHE[name] = (seq, model)
        return seq, model
    model = cas_register()

    # the wide tier runs at overlap 32 (vs 8): ~4x the in-flight ops per
    # instant, so every level's candidate set — and the pruned frontier
    # — is wide; everything else matches the register tiers
    overlap = 32 if name == "10k64" else 8

    def gen(n):
        rng = random.Random(f"bench-{name}")
        h = register_history(rng, n_ops=n, n_procs=n_procs,
                             overlap=overlap, crash_p=0.002,
                             max_crashes=8, n_values=4)
        return corrupt_read(rng, h, at=0.98)

    _, seq = _resolve_nominal(name, gen,
                              lambda h: encode_ops(h, model.f_codes),
                              n_ops, lo_guess=int(n_ops * 1.35))
    _SEQ_CACHE[name] = (seq, model)
    return seq, model


#: BENCH_BATCH_KEYS: contract tests shrink the batch tier to run the
#: full decomposed-vs-direct pipeline in seconds, not minutes
N_BATCH_KEYS = int(os.environ.get("BENCH_BATCH_KEYS", "256"))


def make_batch_key(k: int):
    """BASELINE config #3, one key: a 128-op 8-proc register history
    (every 4th corrupted).  Module-level so the multiprocess comparator
    can rebuild key k in a spawned worker."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    rng = random.Random(f"bench-batch-{k}")
    h = register_history(rng, n_ops=128, n_procs=8, overlap=4,
                         crash_p=0.01, max_crashes=2, n_values=4)
    if k % 4 == 0:
        h = corrupt_read(rng, h, at=0.85)
    return encode_ops(h, model.f_codes), model


def make_batch(n_keys: int = N_BATCH_KEYS):
    seqs = []
    model = None
    for k in range(n_keys):
        s, model = make_batch_key(k)
        seqs.append(s)
    return seqs, model


def _remaining() -> float:
    return BUDGET_S - (time.time() - T0)


DETAIL_PATH = os.path.join(REPO, "BENCH_detail.json")
#: hard ceiling on the emitted stdout line: the driver records only a
#: ~2000-char tail of stdout, and r3+r4 both shipped `parsed: null`
#: because the full detail blob blew through it (VERDICT r4 weak #1)
_COMPACT_LIMIT = 1400


def _tier_mini(d: dict) -> list:
    """[backend, verdict, device seconds] from a tier detail dict."""
    v = d.get("device_verdict")
    if v is None:
        v = d.get("valid")
    return [d.get("backend"), v,
            d.get("device_seconds") if d.get("device_seconds") is not None
            else d.get("t_dev")]


def _best_banked_tpu() -> dict | None:
    """Newest banked on-chip evidence under docs/tpu/*/ , compact.
    Full bench headlines (detail.backend == "tpu") outrank tier-child
    JSONs; within a kind, newest file wins."""
    import glob

    best = None  # ((kind_rank, mtime), compact-dict)
    for p in glob.glob(os.path.join(REPO, "docs", "tpu", "*", "*.json")):
        try:
            with open(p) as f:
                j = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(j, dict):
            continue
        rel = os.path.relpath(p, REPO)
        try:
            mt = os.path.getmtime(p)
        except OSError:
            mt = 0.0
        if (j.get("detail") or {}).get("backend") == "tpu":
            c = {"kind": "bench_headline", "source": rel,
                 **{k: j.get(k) for k in ("metric", "value", "unit",
                                          "vs_baseline")}}
            rank = (1, mt)
        elif j.get("backend") == "tpu" and "valid" in j:
            c = {"kind": "tier_child", "source": rel,
                 "valid": j.get("valid"), "t_dev": j.get("t_dev"),
                 "n_ops": j.get("n_ops"), "configs": j.get("configs")}
            rank = (0, mt)
        else:
            continue
        if best is None or rank > best[0]:
            best = (rank, c)
    return best[1] if best else None


def _compact_result(result: dict) -> dict:
    """Shrink the full result to a <= _COMPACT_LIMIT stdout line: the
    headline numbers, a per-tier mini-table, the probe diagnosis, a
    pointer to BENCH_detail.json — and, whenever the live run did not
    land on the TPU, the best BANKED on-chip artifact (tagged
    evidence: "banked") so the driver artifact is never blind to chip
    evidence that exists (VERDICT r5 item 3)."""
    det = result.get("detail") or {}
    cd: dict = {}
    for k in ("backend", "engine", "device_verdict", "valid",
              "device_seconds", "device_seconds_incl_compile",
              "n_ops", "n_keys", "keys_per_sec", "resumed",
              "device_configs", "speedup_vs_host_linear_1core",
              "speedup_vs_host16", "speedup_vs_host_pool",
              "speedup_vs_host_pool_per_core", "host_cpus", "error"):
        if det.get(k) is not None:
            cd[k] = det[k]
    basis = det.get("vs_baseline_basis")
    if basis:
        cd["vs_baseline_basis"] = (basis if len(basis) <= 80
                                   else basis[:77] + "...")
    hl = det.get("host_linear")
    if isinstance(hl, dict):
        cd["host_linear"] = {k: hl.get(k) for k in ("valid", "seconds")}
    pr = det.get("probe")
    if isinstance(pr, dict):
        cd["probe"] = {k: pr[k] for k in
                       ("platform", "waited_s", "tunnel_endpoint_tcp",
                        "restarts") if pr.get(k) is not None}
    tiers = {}
    for k, v in det.items():
        if (k.startswith("tier_") and isinstance(v, dict)
                and "see" not in v):
            tiers[k[5:]] = _tier_mini(v)
    if isinstance(det.get("batch256"), dict):
        tiers["batch256"] = _tier_mini(det["batch256"])
    if tiers:
        cd["tiers"] = tiers
    cd["full_detail"] = "BENCH_detail.json"
    if cd.get("backend") != "tpu":
        banked = _best_banked_tpu()
        if banked:
            banked["evidence"] = "banked"
            cd["banked_tpu"] = banked
    compact = {k: result.get(k) for k in ("metric", "value", "unit",
                                          "vs_baseline")}
    compact["detail"] = cd
    # last-resort trims, least precious first (banked_tpu never drops)
    drop = ["tiers", "probe", "vs_baseline_basis", "host_linear"]
    while len(json.dumps(compact)) > _COMPACT_LIMIT and drop:
        cd.pop(drop.pop(0), None)
    return compact


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    result = _BEST or {
        "metric": "ops-verified/sec, CAS-register history",
        "value": None, "unit": "ops/s", "vs_baseline": None,
        "detail": {"error": "no tier completed within budget"},
    }
    if _EXTRA and "detail" in result:
        result["detail"].update(_EXTRA)
    _EMITTED = True
    try:
        with open(DETAIL_PATH, "w") as f:
            json.dump(result, f, indent=1)
    except OSError as e:
        print(f"bench: could not write {DETAIL_PATH}: {e}",
              file=sys.stderr)
    try:
        compact = _compact_result(result)
    except Exception as e:  # noqa: BLE001 — never lose the emit
        print(f"bench: compact emit failed ({e!r}); emitting full",
              file=sys.stderr)
        compact = result
    print(json.dumps(compact), flush=True)


def _kill_proc(proc) -> None:
    """Kill (if alive) and release a probe/child Popen: stderr log
    handle and stdout pipe both close, so probe restarts don't leak
    fds across a long bench."""
    if proc is None:
        return
    if proc.poll() is None:
        try:
            proc.kill()
            proc.wait(timeout=5)
        except Exception:
            pass
    for f in (getattr(proc, "_errf", None), proc.stdout):
        if f is not None:
            try:
                f.close()
            except Exception:
                pass


def _reap_procs():
    for proc in (_PROBE, _CHILD):
        _kill_proc(proc)


def _bail(why: str):
    print(f"bench: {why} after {time.time()-T0:.0f}s; emitting "
          "best-so-far", file=sys.stderr)
    _emit()
    _reap_procs()
    os._exit(0)


def _on_signal(signum, frame):
    _bail(f"signal {signum}")


def _install_guards():
    for _sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM,
                 signal.SIGHUP):
        try:
            signal.signal(_sig, _on_signal)
        except (OSError, ValueError):
            pass

    # Two layers of deadline enforcement: an alarm (covers pure-Python
    # blocking) and a watchdog thread (covers the main thread stuck in
    # non-interruptible C code).
    signal.alarm(max(10, int(BUDGET_S - 5)))

    import threading

    def _watchdog():
        time.sleep(max(10, BUDGET_S - 2))
        _bail("watchdog deadline")

    threading.Thread(target=_watchdog, daemon=True).start()


PROBE_LOG = os.path.join(REPO, ".bench_probe.log")


def start_probe() -> subprocess.Popen:
    """Warm/probe the accelerator backend in a subprocess (it may block
    for minutes; it may never return if the tunnel is down).  stderr
    goes to PROBE_LOG so a cpu fallback is diagnosable from the emitted
    JSON (VERDICT r3 weak #2: three rounds of fallbacks with the reason
    printed to a lost stderr)."""
    errf = open(PROBE_LOG, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import time,sys; t0=time.time();"
         "import jax; d=jax.devices()[0]; print('PLATFORM', d.platform);"
         "print('DEVICES', len(jax.devices()), file=sys.stderr);"
         "import jax.numpy as jnp;"
         "x=jnp.ones((128,128));(x@x).block_until_ready();"
         "print('WARM %.1fs' % (time.time()-t0))"],
        stdout=subprocess.PIPE, stderr=errf, text=True)
    proc._errf = errf  # close at reap
    return proc


#: the axon terminal's local TCP endpoint (observed listener in the r4
#: image; only a diagnostic probe target, never a data path)
TUNNEL_PORT = int(os.environ.get("BENCH_TUNNEL_PORT", "2024"))


def _tunnel_endpoint_state() -> str:
    """TCP state of the axon terminal's local endpoint (the r4 wedge
    signature: the port ACCEPTS while the worker session beyond is
    dead, so 'open' + a hung probe means wedged-worker; 'closed' means
    no tunnel at all; 'timeout' means a listener that stopped
    answering — present but unresponsive)."""
    import socket

    try:
        with socket.create_connection(("127.0.0.1", TUNNEL_PORT),
                                      timeout=2):
            return "open"
    except (TimeoutError, socket.timeout):
        return "timeout"
    except OSError:
        return "closed"


def probe_diag(proc: "subprocess.Popen | None", platform,
               waited_s: float) -> dict:
    """Verbatim probe evidence for the emitted JSON."""
    d = {"platform": platform, "waited_s": round(waited_s, 1),
         "returncode": None if proc is None else proc.poll(),
         "probe_budget_s": PROBE_S,
         "tunnel_endpoint_tcp": _tunnel_endpoint_state()}
    try:
        with open(PROBE_LOG) as f:
            tail = f.read()[-2000:]
        d["stderr_tail"] = tail if tail.strip() else None
    except OSError:
        d["stderr_tail"] = None
    return d


def finish_probe(proc: subprocess.Popen, timeout: float, *,
                 keep_alive: bool = False) -> str | None:
    """Wait for the probe; returns the platform name or None.

    With ``keep_alive``, a timed-out probe is left RUNNING: a cold axon
    tunnel has been observed to need ~9 minutes of first-touch, so the
    CPU ladder runs while the probe keeps warming, and the accelerator
    gets a second chance afterwards (see main's per-tier late re-check)."""
    try:
        out, _ = proc.communicate(timeout=max(1.0, timeout))
    except subprocess.TimeoutExpired:
        if not keep_alive:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        return None
    if proc.returncode != 0 or not out:
        return None
    platform = None
    for line in out.splitlines():
        if line.startswith("PLATFORM "):
            platform = line.split(None, 1)[1].strip()
    return platform


# ---------------------------------------------------------------------------
# decomposed-vs-direct reporting (ISSUE 1: configs 3 and 5)
# ---------------------------------------------------------------------------


def _batch_decomposed(lin, seqs, model, budget, direct_results,
                      t_direct) -> dict:
    """Config 3 decomposed-vs-direct: two passes through the canonical-
    hash verdict cache (jepsen_tpu/decompose/).  The cold pass pays the
    searches and fills the cache (or hits it, if a prior bench run left
    it warm — that's the cross-run hit rate the cache exists for); the
    warm pass measures pure cache service.  The cache file persists
    under store/ via store.py's BASE, so reruns start warm."""
    from jepsen_tpu.decompose.cache import VerdictCache, default_cache_path

    cache_path = os.environ.get(
        "BENCH_DECOMPOSE_CACHE",
        default_cache_path(os.path.join(REPO, "store")))
    cache = VerdictCache(cache_path)
    prior_entries = len(cache)
    t0 = time.perf_counter()
    r_cold = lin.search_batch(seqs, model, budget=budget,
                              decompose=True, decompose_cache=cache)
    t_cold = time.perf_counter() - t0
    cold = r_cold[0].get("decompose_batch") or {}
    t0 = time.perf_counter()
    r_warm = lin.search_batch(seqs, model, budget=budget,
                              decompose=True, decompose_cache=cache)
    t_warm = time.perf_counter() - t0
    warm = r_warm[0].get("decompose_batch") or {}
    # agreement is judged on keys the direct engine DECIDED: the layer
    # deciding a key direct left "unknown" is an added verdict, not a
    # soundness disagreement (it must never flip a decided one)
    direct_v = [r["valid"] for r in direct_results]
    agree = all(rc["valid"] == dv and rw["valid"] == dv
                for rc, rw, dv in zip(r_cold, r_warm, direct_v)
                if dv in (True, False))
    return {
        "cache_path": os.path.relpath(cache_path, REPO),
        "prior_cache_entries": prior_entries,
        "t_cold": round(t_cold, 3),
        "t_warm": round(t_warm, 3),
        "cold_hits": cold.get("cache_hits"),
        "cold_hit_rate": cold.get("hit_rate"),
        "cold_deduped": cold.get("deduped"),
        "cold_searched": cold.get("searched"),
        "warm_hits": warm.get("cache_hits"),
        "warm_hit_rate": warm.get("hit_rate"),
        "verdicts_agree": agree,
        "speedup_cold_vs_direct": (round(t_direct / t_cold, 2)
                                   if t_cold > 0 else None),
        "speedup_warm_vs_direct": (round(t_direct / t_warm, 2)
                                   if t_warm > 0 else None),
    }


def _wide_outlier_key():
    """One deliberately WIDE key (512 ops, overlap 16, corrupted so it
    must ride the device): appended to the config-3 batch it forces
    the single fused batch to pad all other keys to its dims — the
    mixed-size shape the bucketed scheduler (checker/bucket.py)
    exists for.  Corrupted EARLY (at=0.35): padding efficiency is a
    function of dims alone, while verdict-search cost scales with the
    obstruction depth — a late corruption made the probe's two passes
    cost minutes of pure search on a cold CPU."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    rng = random.Random("bench-batch-wide")
    h = register_history(rng, n_ops=512, n_procs=16, overlap=16,
                         crash_p=0.01, max_crashes=2, n_values=6)
    return encode_ops(corrupt_read(rng, h, at=0.35), model.f_codes)


def _batch_bucketed(lin, seqs, model, budget, direct_results,
                    left_s: float | None = None) -> dict:
    """ISSUE 2 acceptance evidence: the mixed-size batch (config 3
    shape plus one wide outlier key), bucketed vs single-fused —
    verdict parity, padding efficiency both ways (useful_ops /
    padded_ops), per-bucket detail, and kernel-cache hit counts.

    Cost containment (the probe must never eat the batch tier): it
    runs on a config-3 SUBSET (BENCH_BUCKET_KEYS, default 16), with
    its own config-budget cap (search_batch has no wall-clock cancel,
    so the budget is the bound — exhausted keys report "unknown" in
    BOTH passes, parity intact), and it is skipped outright when the
    tier has under ~30s left (``left_s``)."""
    if left_s is not None and left_s < 30.0:
        return {"skipped": f"tier budget exhausted ({left_s:.0f}s left)"}
    n_sub = int(os.environ.get("BENCH_BUCKET_KEYS", "16"))
    seqs = seqs[:n_sub]
    direct_results = direct_results[:n_sub]
    budget = min(budget, 500_000)
    mixed = seqs + [_wide_outlier_key()]
    t0 = time.perf_counter()
    r_fused = lin.search_batch(mixed, model, budget=budget,
                               bucket=False)
    t_fused = time.perf_counter() - t0
    t0 = time.perf_counter()
    r_buck = lin.search_batch(mixed, model, budget=budget, bucket=True)
    t_buck = time.perf_counter() - t0
    st = r_buck[0].get("bucket_batch") or {}
    return {
        "n_keys": len(mixed),
        "t_fused": round(t_fused, 3),
        "t_bucketed": round(t_buck, 3),
        "verdicts_agree_fused": all(
            a["valid"] == b["valid"] for a, b in zip(r_fused, r_buck)),
        # the plain config-3 results (no outlier) must agree too —
        # bucketing may only relabel work, never flip a verdict.
        # Judged on keys the probe DECIDED (its budget is capped below
        # the direct pass's; an unknown is a budget artifact, not a
        # flip — same convention as the decomposed comparison)
        "verdicts_agree_direct": all(
            a["valid"] == d["valid"] for a, d in
            zip(r_buck[:len(direct_results)], direct_results)
            if a["valid"] in (True, False)),
        "n_buckets": st.get("n_buckets"),
        "padding_efficiency_bucketed": st.get("padding_efficiency"),
        "padding_efficiency_fused": st.get("fused_padding_efficiency"),
        "per_bucket": st.get("buckets"),
        "kernel_cache": st.get("kernel_cache"),
    }


def _single_decomposed(seq, model, budget, direct_valid,
                       t_direct) -> dict:
    """Config 5 decomposed-vs-direct: value partitioning + quiescence
    cuts on one big history, host-side, time-capped.  Reported numbers
    are honest about what decomposition found: when the history yields
    no cells/segments/blocks at all (this tier's generator keeps >=8
    ops permanently in flight and reuses 4 values, so neither cutter
    fires), the probe says so and does NOT re-run the direct engine
    under a "decomposed" label."""
    from jepsen_tpu.decompose.engine import check_opseq_decomposed
    from jepsen_tpu.decompose.partition import (quiescence_segments,
                                                value_block_verdict)

    cap = float(os.environ.get("BENCH_DECOMPOSE_S", "90"))
    t0 = time.perf_counter()
    n_segs = len(quiescence_segments(seq))
    vb = value_block_verdict(seq, model)
    if n_segs <= 1 and vb is None and model.name != "multi-register":
        return {"applies": False, "cells": 1, "segments": n_segs,
                "probe_seconds": round(time.perf_counter() - t0, 3),
                "note": "no value partition (non-unique writes) and no "
                        "quiescent point: the direct engine carries "
                        "this tier"}
    try:
        rd = check_opseq_decomposed(seq, model, sub_max_configs=budget,
                                    deadline=time.perf_counter() + cap)
    except Exception as e:  # noqa: BLE001 — report, never kill the tier
        rd = {"valid": "unknown", "configs": 0,
              "decompose": {"error": repr(e)}}
    t_dec = time.perf_counter() - t0
    d = rd.get("decompose") or {}
    decided = (rd.get("valid") in (True, False)
               and direct_valid in (True, False))
    return {
        "applies": True,
        "valid": rd.get("valid"), "seconds": round(t_dec, 3),
        "configs": rd.get("configs"),
        "cells": d.get("cells"), "segments": d.get("segments"),
        "methods": d.get("methods"),
        "agrees_direct": (rd.get("valid") == direct_valid
                          if decided else None),
        "speedup_vs_direct": (round(t_direct / t_dec, 2)
                              if decided and t_dec > 0 else None),
    }


# ---------------------------------------------------------------------------
# child: run one tier in this process, print one JSON line
# ---------------------------------------------------------------------------


def _child_platform_pin():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the sitecustomize-registered TPU plugin ignores the env var
        # alone; the config pin must land before first backend touch
        # (tests/conftest.py:10-23)
        jax.config.update("jax_platforms", "cpu")
    try:
        # persistent XLA compile cache: repeated bench runs (and the
        # CPU-retry child) skip recompilation.  The env knob shares
        # one cache dir with the CLI's --compile-cache-dir so every
        # process family warms the same store.
        jax.config.update("jax_compilation_cache_dir",
                          os.environ.get("JEPSEN_TPU_COMPILE_CACHE_DIR")
                          or os.path.join(REPO, ".jax_cache"))
    except Exception:
        pass
    return jax


def run_tier_child(name: str, budget: int) -> None:
    jax = _child_platform_pin()

    from jepsen_tpu.checker import linearizable as lin

    tier_deadline = float(os.environ.get("BENCH_TIER_S", "150"))

    if name == "batch256":
        seqs, model = make_batch()
        t_tier0 = time.perf_counter()
        t0 = time.perf_counter()
        results = lin.search_batch(seqs, model, budget=budget)
        t_first = t_dev = time.perf_counter() - t0
        # compile-free re-time only when the first pass left room for it
        if t_first < tier_deadline * 0.5:
            t0 = time.perf_counter()
            results = lin.search_batch(seqs, model, budget=budget)
            t_dev = time.perf_counter() - t0
        n_ops = sum(len(s) for s in seqs)
        n_valid = sum(1 for r in results if r["valid"] is True)
        n_bad = sum(1 for r in results if r["valid"] is False)
        n_unk = len(results) - n_valid - n_bad
        dec = (_batch_decomposed(lin, seqs, model, budget, results,
                                 t_dev)
               if os.environ.get("BENCH_DECOMPOSE", "1") != "0"
               else None)
        buck = (_batch_bucketed(
                    lin, seqs, model, budget, results,
                    left_s=tier_deadline - (time.perf_counter()
                                            - t_tier0))
                if os.environ.get("BENCH_BUCKETS", "1") != "0"
                else None)
        print(json.dumps({
            "configs": sum(r["configs"] for r in results),
            "t_dev": t_dev, "t_first": t_first,
            "valid": f"{n_valid} valid / {n_bad} invalid / "
                     f"{n_unk} unknown of {len(results)} keys",
            "verdicts": [r["valid"] if isinstance(r["valid"], bool)
                         else "unknown" for r in results],
            "engine": results[0].get("engine"),
            "n_ops": n_ops, "n_keys": len(seqs),
            "backend": jax.default_backend(),
            "decomposed": dec,
            "bucketed": buck,
        }), flush=True)
        return

    seq, model = make_seq(name)

    slices: list[tuple[float, int]] = []  # (wall time, cumulative configs)

    # cross-run checkpointing: a wedged-tunnel kill (observed r4 — the
    # 10k child died at 950s with every explored config lost) must not
    # restart the search from scratch.  Every slice persists the carry;
    # the next child — same tier on the next tunnel window, or the
    # pinned-CPU retry — resumes it, and the reported timing carries an
    # honest "resumed" flag plus the cumulative elapsed seconds.
    # BENCH_CKPT_DIR= (empty) disables.
    ckpt_dir = os.environ.get("BENCH_CKPT_DIR",
                              os.path.join(REPO, ".bench_ckpt"))
    ckpt = os.path.join(ckpt_dir, f"{name}.npz") if ckpt_dir else None
    prior_elapsed = 0.0
    prior_slices = 0
    resumed = False
    prior_backends: set = set()
    decided_pending = False
    if ckpt:
        os.makedirs(ckpt_dir, exist_ok=True)
        if not os.path.exists(ckpt):
            # an orphaned meta (npz removed, meta unlink failed or the
            # child died between the two removes) must not leak stale
            # accounting — phantom elapsed/backends — into a run that
            # can never resume the carry it describes
            try:
                os.remove(ckpt + ".meta.json")
            except OSError:
                pass
        else:
            try:
                with open(ckpt + ".meta.json") as f:
                    m = json.load(f)
                prior_elapsed = float(m.get("elapsed", 0.0))
                prior_slices = int(m.get("slices", 0))
                prior_backends = set(m.get("backends", []))
                decided_pending = bool(m.get("decided_pending_tpu"))
            except (OSError, ValueError):
                pass

    t0 = time.perf_counter()
    backend_now = jax.default_backend()

    last_save = [0.0]

    def on_slice(carry, dims):
        slices.append((time.perf_counter(), int(carry[3])))
        # throttled: an every-slice save would pull the whole carry
        # host-side between timed dispatches (hundreds of KB per 0.5s
        # slice at wide frontiers) and bill the npz writes into the
        # reported search time; a 10s cadence costs a wedge at most
        # 10s of progress
        now = time.perf_counter()
        if ckpt and now - last_save[0] > 10.0:
            last_save[0] = now
            lin.save_checkpoint(ckpt + ".tmp.npz", carry, dims, model,
                                budget, seq=seq)
            os.replace(ckpt + ".tmp.npz", ckpt)
            # read-modify-write: fields other runs own (notably
            # decided_pending_tpu from a CPU decide) must survive a
            # TPU child's throttled saves — a wedge after a fresh-dict
            # write would re-arm the CPU-replay loop this flag stops
            try:
                with open(ckpt + ".meta.json") as f:
                    m = json.load(f)
            except (OSError, ValueError):
                m = {}
            m.update({"elapsed": prior_elapsed
                      + (time.perf_counter() - t0),
                      "slices": prior_slices + len(slices),
                      "backends": sorted(prior_backends
                                         | {backend_now})})
            tmp = ckpt + ".meta.tmp"
            with open(tmp, "w") as f:
                json.dump(m, f)
            os.replace(tmp, ckpt + ".meta.json")

    out = None
    if (ckpt and os.path.exists(ckpt) and decided_pending
            and backend_now == "cpu"):
        # this carry is DECIDED (a CPU fallback finished a search that
        # TPU windows had accumulated) and is banked awaiting one
        # on-chip confirmation slice.  A CPU child must neither replay
        # it (ADVICE r4: every later CPU run re-decided and re-reported
        # the verdict as resumed with ever-growing cumulative elapsed)
        # nor overwrite it — run fresh, checkpoint-free, with fresh
        # accounting.
        print("bench: checkpoint is decided-pending-tpu; CPU child "
              "runs fresh without touching it", file=sys.stderr)
        ckpt = None
        prior_elapsed, prior_slices = 0.0, 0
        prior_backends = set()
    if ckpt and os.path.exists(ckpt):
        try:
            out = lin.resume_opseq(seq, model, ckpt, on_slice=on_slice,
                                   deadline=t0 + tier_deadline)
            resumed = True
        except Exception as e:  # noqa: BLE001 — stale/foreign checkpoint
            print(f"bench: checkpoint resume failed ({e!r}); searching "
                  "fresh", file=sys.stderr)
            # the stale files and their accounting must not leak into
            # the fresh run (a phantom "tpu" in prior_backends would arm
            # the keep-checkpoint-on-decide path forever; phantom
            # elapsed would inflate cumulative time)
            prior_elapsed, prior_slices = 0.0, 0
            prior_backends = set()
            # slices recorded during the failed attempt would corrupt
            # the rate telescoping (the fresh run's config counter
            # restarts near 0 — negative deltas across the boundary)
            slices.clear()
            for p in (ckpt, ckpt + ".meta.json"):
                try:
                    os.remove(p)
                except OSError:
                    pass
            t0 = time.perf_counter()
    if out is None:
        out = lin.search_opseq(seq, model, budget=budget,
                               deadline=t0 + tier_deadline,
                               on_slice=on_slice)
    t_first = time.perf_counter() - t0
    if ckpt and out["valid"] in (True, False):
        # decided: later runs must start fresh, not replay a finished
        # carry (and the re-time below must not find a checkpoint).
        # EXCEPT: a CPU fallback deciding a search that TPU windows had
        # been accumulating must not destroy that accumulation — the
        # on-chip decision is the artifact the checkpoint system exists
        # to produce; keep the carry so the next tunnel window finishes
        # it on the TPU (one near-final slice) and deletes it then.
        if not (backend_now == "cpu" and "tpu" in prior_backends):
            for p in (ckpt, ckpt + ".meta.json"):
                try:
                    os.remove(p)
                except OSError:
                    pass
        else:
            # mark the banked carry DECIDED: later CPU children run
            # fresh (see decided_pending above) and only a TPU child
            # resumes it — one near-final slice confirms on-chip and
            # deletes it via the branch above
            try:
                with open(ckpt + ".meta.json") as f:
                    m = json.load(f)
            except (OSError, ValueError):
                m = {}
            m["decided_pending_tpu"] = True
            m["verdict_cpu"] = out["valid"]
            tmp = ckpt + ".meta.tmp"
            with open(tmp, "w") as f:
                json.dump(m, f)
            os.replace(tmp, ckpt + ".meta.json")
    t_dev = t_first  # compile-inclusive, as a floor
    # re-run compile-free when the first run finished well under the
    # deadline (then timing measures the kernel, not the compile).
    # A RESUMED run never re-times: its fresh re-run could blow the
    # deadline and replace a decided verdict with an unknown one —
    # the resumed timing is reported as cumulative instead.
    if not resumed and t_first < tier_deadline * 0.6:
        t0 = time.perf_counter()
        out = lin.search_opseq(seq, model, budget=budget,
                               deadline=t0 + tier_deadline)
        t_dev = time.perf_counter() - t0
        rate = out["configs"] / t_dev if t_dev > 0 else None
    else:
        # deadline-bounded run: estimate steady-state throughput from the
        # slice timeline, dropping compile-dominated outlier slices (each
        # frontier-width change recompiles once; those slices' wall time
        # is compiler, not search).  Rates telescope over CONTIGUOUS runs
        # of kept slices — a width change resets the carry to the last
        # clean pre-overflow state, so the cumulative config counter can
        # regress across an excluded slice; telescoping per segment never
        # double-counts the re-run work.
        rate = None
        if len(slices) >= 3:
            dts = [slices[i + 1][0] - slices[i][0]
                   for i in range(len(slices) - 1)]
            med = sorted(dts)[len(dts) // 2]
            tot_t = tot_c = 0.0
            seg_start = None  # index into slices of current segment head
            for i, dt in enumerate(dts):
                if dt <= 4 * med:
                    if seg_start is None:
                        seg_start = i
                else:
                    if seg_start is not None:
                        tot_t += slices[i][0] - slices[seg_start][0]
                        tot_c += slices[i][1] - slices[seg_start][1]
                    seg_start = None
            if seg_start is not None:
                tot_t += slices[-1][0] - slices[seg_start][0]
                tot_c += slices[-1][1] - slices[seg_start][1]
            if tot_t > 0 and tot_c > 0:
                rate = tot_c / tot_t
        if rate is None and t_dev > 0:
            # a resumed carry's configs counter is CUMULATIVE across
            # contributing runs — divide by the cumulative elapsed, not
            # this run's tail, or a one-slice resumed run reports the
            # whole search's work at this run's wall clock
            rate = out["configs"] / (prior_elapsed + t_dev
                                     if resumed else t_dev)
    # ISSUE 1 config 5: decomposed-vs-direct on the 10k-op tiers.
    # The direct basis matches the rate computation above: cumulative
    # SEARCH seconds, never the compile-inclusive wall time.
    dec = (_single_decomposed(seq, model, budget, out["valid"],
                              prior_elapsed + t_dev
                              if resumed else t_dev)
           if (name in ("10k", "10k64", "10kuniq")
               and os.environ.get("BENCH_DECOMPOSE", "1") != "0")
           else None)
    print(json.dumps({
        "configs": out["configs"],
        "max_depth": out.get("max_depth"),
        "t_dev": t_dev,
        "t_first": t_first,
        "rate": rate,
        "valid": out["valid"],
        "window": out.get("window"),
        "concurrency": out.get("concurrency"),
        "engine": out.get("engine"),
        "n_ops": len(seq),
        "backend": jax.default_backend(),
        "decomposed": dec,
        "resumed": resumed,
        "elapsed_total": round(prior_elapsed + t_first, 3),
        # every backend that contributed search time to this verdict
        # (cumulative results must not let a near-finished CPU carry
        # masquerade as accelerator work, or vice versa)
        "backends_contributing": sorted(prior_backends | {backend_now}),
    }), flush=True)


def run_tier(name: str, budget: int, tier_s: float, *, force_cpu: bool,
             timeout: float, ckpt: bool = True) -> dict | None:
    """Spawn a tier child; returns its parsed JSON or None.  ``ckpt=
    False`` disables checkpoint resume/save in the child (comparison
    siblings must not inherit another backend's accumulated carry)."""
    global _CHILD
    env = dict(os.environ)
    env["BENCH_TIER_S"] = str(tier_s)
    if not ckpt:
        env["BENCH_CKPT_DIR"] = ""
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    proc = _CHILD = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--run-tier", name, "--budget", str(budget)],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env)
    try:
        out, _ = proc.communicate(timeout=max(5.0, timeout))
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        print(f"bench: tier {name} child timed out ({timeout:.0f}s)",
              file=sys.stderr)
        return None
    if proc.returncode != 0 or not out.strip():
        print(f"bench: tier {name} child failed rc={proc.returncode}",
              file=sys.stderr)
        return None
    try:
        return json.loads(out.strip().splitlines()[-1])
    except json.JSONDecodeError:
        return None


def batch_stats(res: dict, host: dict, t_dev: float) -> dict:
    """Per-core-honest batch comparison (VERDICT r3 item 2/6): the pool
    number is stated per core, so a 1-process pool cannot masquerade as
    a multi-core baseline, and the 16-core figure is an explicit linear
    extrapolation (independent keys scale ~linearly across cores)."""
    hp = (host.get("batch256") or {}).get("host_pool") or {}
    s: dict = {"host_pool": hp or None}
    dev_keys_s = res["n_keys"] / t_dev if t_dev > 0 else None
    s["device_keys_per_sec"] = round(dev_keys_s, 1) if dev_keys_s else None
    if hp.get("keys_done") and hp.get("seconds"):
        pool_keys_s = hp["keys_done"] / hp["seconds"]
        per_core = pool_keys_s / max(1, hp.get("n_procs") or 1)
        t_full = hp["seconds"] * hp["n_keys"] / hp["keys_done"]
        s["host_pool_keys_per_sec"] = round(pool_keys_s, 1)
        s["host_pool_keys_per_sec_per_core"] = round(per_core, 1)
        s["speedup_vs_host_pool"] = (round(t_full / t_dev, 2)
                                     if t_dev > 0 else None)
        s["speedup_vs_host_pool_per_core"] = (
            round(dev_keys_s / per_core, 2) if dev_keys_s else None)
        # 16-core pool extrapolation for vs_baseline
        t16 = res["n_keys"] / (per_core * 16)
        measured = (hp.get("n_procs") or 0) >= 8
        s["vs_baseline"] = round(t16 / t_dev, 2) if t_dev > 0 else None
        s["vs_baseline_basis"] = (
            f"measured {hp['n_procs']}-process pool scaled to 16 cores"
            if measured else
            "EXTRAPOLATED: 16-core pool modeled as 16x the measured "
            f"per-core rate ({round(per_core, 1)} keys/s/core on "
            f"{hp.get('n_procs')} proc(s)); independent keys scale "
            "~linearly across cores")
    else:
        s["vs_baseline"] = None
        s["vs_baseline_basis"] = None
    return s


def batch_detail(res: dict, host: dict, t_dev: float) -> dict:
    return {
        **{k: res[k] for k in ("configs", "valid", "engine",
                               "n_keys", "backend")},
        "device_seconds": round(t_dev, 3),
        "device_seconds_incl_compile": round(res["t_first"], 3),
        "keys_per_sec": round(res["n_keys"] / t_dev, 1),
        "decomposed": res.get("decomposed"),
        "bucketed": res.get("bucketed"),
        **batch_stats(res, host, t_dev),
    }


def batch_headline(res: dict, host: dict, t_dev: float) -> dict:
    s = batch_stats(res, host, t_dev)
    return {
        "metric": "independent-key histories checked/sec, "
                  f"{res['n_keys']}-key batch (128-op, "
                  "8-proc each; 1/4 corrupted), "
                  f"{res['backend']} backend",
        "value": round(res["n_keys"] / t_dev, 1),
        "unit": "keys/s",
        "vs_baseline": s.get("vs_baseline"),
        "detail": {"backend": res["backend"],
                   "vs_baseline_basis": s.get("vs_baseline_basis"),
                   **{k: v for k, v in s.items()
                      if k not in ("vs_baseline", "vs_baseline_basis")}},
    }


# ---------------------------------------------------------------------------
# host comparators
# ---------------------------------------------------------------------------


def host_comparators(tiers) -> dict:
    """Per-tier host baselines: single-core `linear` and, when enough
    cores exist, the multiprocess portfolio (checker/parallel.py).
    Runs while the backend probe warms in its subprocess."""
    from jepsen_tpu.checker import parallel as par
    from jepsen_tpu.checker.linear import check_opseq_linear

    cores = os.cpu_count() or 1
    n_procs = min(16, cores)
    out: dict = {"host_cpus": cores}
    # batch has its own pool comparator below.  The wide tiers (10k64,
    # 10kuniq) run LAST with their own env-tunable caps instead of a
    # share — they must never dilute the 10k's cap below its ~52s
    # decide time, but must also never ship comparator-free (VERDICT
    # r4 weak #4: an unknown verdict with host_linear null is a row
    # with no meaning); an undecided host run still reports seconds +
    # configs.
    late = ("10k64", "10kuniq")
    measured = [t for t in tiers
                if not t[0].startswith("batch") and t[0] not in late]
    share = HOST_S / max(1, len(measured))
    wide = [t for t in tiers if t[0] in late]
    for name, _n_ops, _p, _b, _h, _t in measured + wide:
        if name in late:
            share = float(os.environ.get(
                f"BENCH_HOST_{name.upper()}_S", "150"))
        seq, model = make_seq(name)
        cap = max(10.0, min(share, _remaining() - 120))
        t0 = time.perf_counter()
        r = check_opseq_linear(seq, model,
                               deadline=time.perf_counter() + cap)
        t_lin = time.perf_counter() - t0
        out[name] = {"host_linear": {
            "valid": r["valid"], "seconds": round(t_lin, 3),
            "configs": r["configs"],
            "failing_depth": r.get("max_depth")
            if r["valid"] is False else None}}
        print(f"bench: host_linear[{name}] {r['valid']} in {t_lin:.1f}s "
              f"({r['configs']} configs)", file=sys.stderr)
        if n_procs >= 2 and _remaining() > 180:
            pr = par.portfolio_check(make_seq, (name,), n_procs=n_procs,
                                     deadline_s=cap)
            out[name]["host16"] = {
                "valid": pr.get("valid"),
                "seconds": round(pr.get("seconds", 0.0), 3),
                "engine": pr.get("engine"), "n_procs": pr.get("n_procs")}
            print(f"bench: host16[{name}] {pr.get('valid')} in "
                  f"{pr.get('seconds', 0):.1f}s via {pr.get('engine')}",
                  file=sys.stderr)
    # batch-tier pool comparator
    if not QUICK and _remaining() > 150:
        bp = par.batch_check_pool(make_batch_key, N_BATCH_KEYS,
                                  n_procs=n_procs,
                                  deadline_s=max(20.0, min(
                                      HOST_S, _remaining() - 120)))
        out["batch256"] = {"host_pool": {
            "keys_done": bp["keys_done"], "n_keys": N_BATCH_KEYS,
            "seconds": round(bp["seconds"], 3),
            "configs": bp["configs"], "n_procs": bp["n_procs"]}}
        print(f"bench: host_pool[batch256] {bp['keys_done']}/"
              f"{N_BATCH_KEYS} keys in {bp['seconds']:.1f}s "
              f"({bp['n_procs']} procs)", file=sys.stderr)
    return out


def _hb_probe_queue_tier() -> dict:
    """The constraint-compiler (analyze/constraints.py) leg of the
    probe: decided-fast fraction over a random queue-history sample
    (valid + corrupted, unordered + FIFO), and the streamed total-queue
    fold's detection latency on a synthetic lost-acked-enqueue history
    (events from the lost ack to the verdict flip — the metric the
    queue campaign cells now record per cell)."""
    import random as _random

    from jepsen_tpu.analyze.constraints import analyze_constraints
    from jepsen_tpu.history import encode_ops, info_op, invoke_op, ok_op
    from jepsen_tpu.models import fifo_queue, unordered_queue
    from jepsen_tpu.stream.checker import TotalFoldStream
    from jepsen_tpu.synth import (
        corrupt_dequeue,
        sim_queue_history,
        swap_dequeues,
    )

    n_hist = int(os.environ.get("BENCH_HB_QUEUE_N", "60"))
    decided = 0
    t0 = time.perf_counter()
    for i in range(n_hist):
        rng = _random.Random(7000 + i)
        fifo = i % 2 == 1
        model = (fifo_queue if fifo else unordered_queue)(33)
        h = sim_queue_history(rng, 28, 4,
                              crash_p=rng.choice([0.0, 0.0, 0.2]),
                              fifo=fifo)
        if rng.random() < 0.5:
            h = (swap_dequeues if rng.random() < 0.5
                 else corrupt_dequeue)(rng, h)
        s = encode_ops(h, model.f_codes)
        if analyze_constraints(s, model).decided is not None:
            decided += 1
    prepass_s = time.perf_counter() - t0

    # streamed lost-ack detection: N acked enqueues, one lost, drain
    # short at 3/4 of the stream — the flip must land AT the drain
    n_jobs = 200
    sink = TotalFoldStream("total-queue")
    t1 = time.perf_counter()
    ev = 0
    for j in range(n_jobs):
        sink.ingest(invoke_op(j % 4, "enqueue", j))
        sink.ingest(ok_op(j % 4, "enqueue", j))
        ev += 2
    sink.ingest(info_op("nemesis", "start", None))
    ev += 1
    sink.ingest(invoke_op(0, "drain", None))
    sink.ingest(ok_op(0, "drain", [j for j in range(n_jobs) if j != 17]))
    ev += 2
    flip_event = sink.verdict()["invalid_event"]
    for j in range(40):  # post-flip traffic the flip did not wait for
        sink.ingest(invoke_op(1, "enqueue", n_jobs + j))
        sink.ingest(ok_op(1, "enqueue", n_jobs + j))
        ev += 2
    final = sink.finalize()
    stream_s = time.perf_counter() - t1
    return {
        "n_histories": n_hist,
        "decided_fast": decided,
        "decided_fraction": round(decided / n_hist, 3),
        "prepass_seconds": round(prepass_s, 3),
        "streamed": {
            "events": ev,
            "invalid_event": flip_event,
            "events_before_finalize": ev - (flip_event or 0),
            "final_valid": final.get("valid"),
            "evidence_kind": (final.get("queue_evidence")
                              or {}).get("kind"),
            "seconds": round(stream_s, 3),
        },
    }


def run_hb_probe(out_path: str | None = None) -> dict:
    """HB-on-vs-off probe over the 10k tiers -> BENCH_hb.json.

    Per tier (10k, 10kuniq, 10k64): the static plan's raw vs pruned
    config bound (``explain()['hb']``), a budget-capped host-sweep
    comparison (explored configs / depth reached with the must-order
    mask on vs off), and — for the decide-fast tier — a traced device
    probe whose ``device.slice`` spans show the search the pre-pass
    removed (the PR-10 bench contract: cite spans, not wall-clock
    alone).  Budgets are env-tunable (BENCH_HB_HOST_CAP,
    BENCH_HB_DEV_BUDGET, BENCH_HB_TIERS); histories are the tier
    generators' own, full size.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from jepsen_tpu import obs as _obs
    from jepsen_tpu.analyze.plan import explain
    from jepsen_tpu.checker.linear import check_opseq_linear
    from jepsen_tpu.checker.linearizable import search_batch

    host_cap = int(os.environ.get("BENCH_HB_HOST_CAP", "400000"))
    dev_budget = int(os.environ.get("BENCH_HB_DEV_BUDGET", "200000"))
    tier_names = [t for t in os.environ.get(
        "BENCH_HB_TIERS", "10k,10kuniq,10k64").split(",") if t]
    _obs.enable(True)
    out: dict = {"host_cap_configs": host_cap,
                 "device_budget": dev_budget, "tiers": {}}

    def device_spans():
        """(count, seconds) over cat="device" spans: device.slice on
        the single/sharded drivers, bucket.device on the bucketed
        ladder — the removed-search evidence either way."""
        sp = [s for s in _obs.recorder(None).spans()
              if s["cat"] == "device"]
        return len(sp), round(sum(s["dur"] for s in sp) / 1e6, 3)

    for name in tier_names:
        seq, model = make_seq(name)
        row: dict = {"n_ops": len(seq), "model": model.name}
        plan = explain(seq, model)
        hb = plan["hb"]
        row["explain"] = {
            "raw_bound_log2": plan["config_upper_bound_log2"],
            "pruned_bound": hb.get("pruned_upper_bound"),
            "decided": hb.get("decided"),
            "reason": hb.get("reason"),
            "must_edges": hb.get("must_edges", 0),
            "edges": hb.get("edges"),
            "window": plan["window"],
            "window_effective": hb.get("window_effective"),
            "prune_ratio": hb.get("prune_ratio"),
        }
        # budget-capped host sweep: with the prune, the same budget
        # reaches deeper (or decides outright at zero configs)
        host = {}
        for flag in (True, False):
            t0 = time.perf_counter()
            r = check_opseq_linear(seq, model, max_configs=host_cap,
                                   lint=False, hb=flag)
            host["on" if flag else "off"] = {
                "valid": r["valid"], "configs": r["configs"],
                "max_depth": r.get("max_depth"),
                "seconds": round(time.perf_counter() - t0, 3),
            }
        row["host_sweep"] = host
        # traced device probe for the decide-fast class: hb-on
        # disposes the key before any device work, hb-off rides the
        # bucketed ladder until the budget — the device.slice span
        # delta IS the removed search
        if row["explain"]["decided"] is not None:
            dev = {}
            for flag in (True, False):
                n0, s0 = device_spans()
                t0 = time.perf_counter()
                r = search_batch([seq], model, budget=dev_budget,
                                 bucket=True, lint=False, hb=flag)[0]
                n1, s1 = device_spans()
                dev["on" if flag else "off"] = {
                    "valid": r["valid"], "engine": r.get("engine"),
                    "configs": int(r.get("configs", 0) or 0),
                    "device_slices": n1 - n0,
                    "device_slice_seconds": round(s1 - s0, 3),
                    "seconds": round(time.perf_counter() - t0, 3),
                }
            row["device_probe"] = dev
        out["tiers"][name] = row
        print(f"hb-probe {name}: decided={row['explain']['decided']} "
              f"must_edges={row['explain']['must_edges']} host "
              f"on/off configs "
              f"{host['on']['configs']}/{host['off']['configs']}",
              file=sys.stderr)
    out["tiers"]["queue"] = _hb_probe_queue_tier()
    path = out_path or os.path.join(REPO, "BENCH_hb.json")
    _obs.write_trace(os.path.join(REPO, "BENCH_trace_hb.json"))
    out["trace"] = "BENCH_trace_hb.json (device.slice / hb.prepass "
    out["trace"] += "spans)"
    with open(path, "w") as f:
        json.dump(out, f, indent=1, default=str)
    print(f"hb-probe -> {path}")
    return out


def run_dpor_probe(out_path: str | None = None) -> dict:
    """DPOR/dedup-on-vs-off probe -> BENCH_dpor.json (phase-2 bench
    contract: cite device spans and config counts, not wall-clock
    alone; spans land in BENCH_trace_dpor.json).

    Three tiers isolate the three reductions:

      * **10k** (cas, hb-undecided): dpor threads the prepass's 1141
        canon edges into the device planes — host sweep depth and
        device configs/spans, dpor on vs off, BOTH with hb on, so the
        delta is the device MASK's;
      * **10kuniq** (unique writes, hb-decides): re-run with hb OFF so
        the device actually searches — the delta is the dead-value
        DEDUP's (every swapped-read value dies shortly after its
        block);
      * **10kdup** (duplicate-heavy writes, hb-tainted: no unique-
        writes algebra at all): duplicate-op edges + dedup are the
        ONLY reductions available — the dynamic layer's own tier.

    Budgets are env-tunable (BENCH_DPOR_HOST_CAP, BENCH_DPOR_DEV_BUDGET,
    BENCH_DPOR_TIERS).
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from jepsen_tpu import obs as _obs
    from jepsen_tpu.analyze.plan import explain
    from jepsen_tpu.checker.linear import check_opseq_linear
    from jepsen_tpu.checker.linearizable import search_batch

    host_cap = int(os.environ.get("BENCH_DPOR_HOST_CAP", "400000"))
    dev_budget = int(os.environ.get("BENCH_DPOR_DEV_BUDGET", "200000"))
    tier_names = [t for t in os.environ.get(
        "BENCH_DPOR_TIERS", "10k,10kuniq,10kdup").split(",") if t]
    _obs.enable(True)
    out: dict = {"host_cap_configs": host_cap,
                 "device_budget": dev_budget, "tiers": {}}

    def device_spans():
        sp = [s for s in _obs.recorder(None).spans()
              if s["cat"] == "device"]
        return len(sp), round(sum(s["dur"] for s in sp) / 1e6, 3)

    def make_tier(name):
        if name == "10kdup":
            from jepsen_tpu.history import encode_ops
            from jepsen_tpu.models import register
            from jepsen_tpu.synth import register_history, \
                swap_read_values

            model = register(0)
            rng = random.Random("bench-10kdup")
            h = register_history(rng, n_ops=10_000, n_procs=8,
                                 overlap=8, crash_p=0.0, cas=False,
                                 n_values=4)
            # a read-value swap (both values written, so no
            # impossible-read decide-fast; duplicates taint the hb
            # algebra): neither the greedy witness nor the prepass
            # disposes it — the tier genuinely searches, and dup
            # edges + dedup are the only reductions in play
            h = swap_read_values(rng, h)
            return encode_ops(h, model.f_codes), model
        return make_seq(name)

    for name in tier_names:
        seq, model = make_tier(name)
        # 10kuniq is decided by the hb prepass; probing the dedup
        # needs the device to actually search, so that tier runs with
        # hb off (the delta is then purely the dynamic layer's)
        hb_flag = name != "10kuniq"
        row: dict = {"n_ops": len(seq), "model": model.name,
                     "hb": hb_flag}
        plan = explain(seq, model)
        dp = plan["dpor"]
        row["explain"] = {
            "dup_edges": dp.get("dup_edges"),
            "masked_rows": dp.get("masked_rows"),
            "mask_coverage": dp.get("mask_coverage"),
            "dedup": dp.get("dedup"),
            "sleep_set_bound": dp.get("sleep_set_bound"),
            "pruned_bound": dp.get("pruned_upper_bound"),
            "prune_ratio": dp.get("prune_ratio"),
        }
        host = {}
        for flag in (True, False):
            t0 = time.perf_counter()
            r = check_opseq_linear(seq, model, max_configs=host_cap,
                                   lint=False, hb=hb_flag, dpor=flag)
            st = r.get("dpor") or {}
            host["on" if flag else "off"] = {
                "valid": r["valid"], "configs": r["configs"],
                "max_depth": r.get("max_depth"),
                "dedup_rewrites": st.get("dedup_rewrites"),
                "dedup_hits": st.get("dedup_hits"),
                "mask_lanes_killed": st.get("mask_lanes_killed"),
                "seconds": round(time.perf_counter() - t0, 3),
            }
        row["host_sweep"] = host
        dev = {}
        for flag in (True, False):
            # warm the kernel caches at a token budget so the measured
            # spans compare steady-state level work, not each leg's
            # first-compile tax (the masked and unmasked kernels are
            # DIFFERENT programs; without the warmup whichever leg ran
            # first ate a compile inside its device spans)
            search_batch([seq], model, budget=500, bucket=True,
                         lint=False, hb=hb_flag, dpor=flag)
            n0, s0 = device_spans()
            t0 = time.perf_counter()
            r = search_batch([seq], model, budget=dev_budget,
                             bucket=True, lint=False, hb=hb_flag,
                             dpor=flag)[0]
            n1, s1 = device_spans()
            dev["on" if flag else "off"] = {
                "valid": r["valid"], "engine": r.get("engine"),
                "configs": int(r.get("configs", 0) or 0),
                "max_depth": int(r.get("max_depth", 0) or 0),
                "device_slices": n1 - n0,
                "device_slice_seconds": round(s1 - s0, 3),
                "seconds": round(time.perf_counter() - t0, 3),
            }
        row["device_probe"] = dev
        out["tiers"][name] = row
        print(f"dpor-probe {name}: dup_edges="
              f"{row['explain']['dup_edges']} host on/off depth "
              f"{host['on']['max_depth']}/{host['off']['max_depth']} "
              f"device on/off configs {dev['on']['configs']}/"
              f"{dev['off']['configs']} spans "
              f"{dev['on']['device_slice_seconds']}s/"
              f"{dev['off']['device_slice_seconds']}s",
              file=sys.stderr)
    out["notes"] = (
        "Primary evidence is CONFIG-COUNT/DEPTH at a fixed budget "
        "(the state-space metric): the mask/dedup reach 13-55% deeper "
        "or decide with ~19% fewer configs.  On the CPU backend the "
        "masked kernel's per-level cost is 2-3x (per-lane pred "
        "gathers dominate a host level), so budget-capped device "
        "spans GROW here even as the searched space shrinks; on TPU "
        "the same check is a few VPU gathers against an op-count-"
        "floored level (docs/tpu/r4) — re-measure there with "
        "tools/tpubench before reading the span columns as a "
        "wall-clock verdict.")
    path = out_path or os.path.join(REPO, "BENCH_dpor.json")
    _obs.write_trace(os.path.join(REPO, "BENCH_trace_dpor.json"))
    out["trace"] = ("BENCH_trace_dpor.json (device.slice / "
                    "bucket.device / hb.prepass spans)")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, default=str)
    print(f"dpor-probe -> {path}")
    return out


_DEVLINT_RAN = False


def _devlint_preflight():
    # devlint preflight: --trace re-records the committed
    # BENCH_trace_*.json evidence, and tools/obs_guard.py holds
    # those traces to the K007 cache-key contract — recording them
    # from kernels that FAIL the device-contract lint would bake
    # drifted compile spans into the repo.  Refuse before spending
    # any accelerator budget.
    global _DEVLINT_RAN
    if "--trace" not in sys.argv or _DEVLINT_RAN:
        return
    _DEVLINT_RAN = True
    from jepsen_tpu.analyze.devlint import run_devlint

    rep = run_devlint()
    if rep["errors"]:
        for d in rep["diagnostics"]:
            print(f"bench: devlint {d['severity'].upper()} "
                  f"{d['code']} {d['message']}", file=sys.stderr)
        print(f"bench: refusing --trace tiers — "
              f"{rep['errors']} device-contract error(s) across "
              f"route(s) {', '.join(rep['routes'])}; fix (or "
              f"suppress with a documented `devlint: ok`) and "
              f"re-run", file=sys.stderr)
        sys.exit(2)
    print(f"bench: devlint preflight ok "
          f"({len(rep['routes'])} kernel route(s) stage clean)",
          file=sys.stderr)


def main():
    global _BEST, _BEST_PRIO, _BEST_TIER, _PROBE

    _install_guards()
    _devlint_preflight()
    probe = _PROBE = start_probe()

    tiers = TIERS[:1] if QUICK else TIERS
    # BENCH_TIER_ORDER: comma-separated tier names — reorder/subset the
    # ladder.  Lets a brief accelerator window be spent on the cheap
    # tiers first (a wedged-mid-run tunnel was observed r4), or on one
    # tier alone; unknown names are ignored.
    order = os.environ.get("BENCH_TIER_ORDER")
    if order and not QUICK:
        by_name = {t[0]: t for t in TIERS}
        picked = [by_name[n] for n in
                  (s.strip() for s in order.split(",")) if n in by_name]
        if picked:
            tiers = picked
            _EXTRA["tier_order"] = [t[0] for t in picked]

    # --- bring up the backend ------------------------------------------
    # short early probe FIRST: when the tunnel is already open, every
    # second belongs to the device tiers (r4: windows lasted ~5-8 min
    # and 69s of one went to host comparators that need no tunnel).
    # Host comparators then run AFTER the device ladder, and the tier
    # headlines are re-recorded against them.
    t_probe0 = time.time()
    early_s = float(os.environ.get("BENCH_EARLY_PROBE_S", "20"))
    platform = finish_probe(probe,
                            min(early_s, max(1.0, _remaining() - 60)),
                            keep_alive=True)
    defer_host = platform is not None and platform != "cpu"
    host: dict = {}
    if not defer_host:
        host = host_comparators(tiers)
        if platform is None:
            platform = finish_probe(probe,
                                    min(PROBE_S, _remaining() - 60),
                                    keep_alive=True)
    cores = host.get("host_cpus", os.cpu_count() or 1)
    _EXTRA["host_cpus"] = cores
    _EXTRA["vs_baseline_convention"] = VS_BASELINE_CONVENTION
    _EXTRA["probe"] = probe_diag(probe, platform, time.time() - t_probe0)
    force_cpu = platform is None
    if force_cpu:
        print("bench: accelerator unreachable within probe budget; "
              "forcing CPU backend (probe left warming for a late "
              "retry)", file=sys.stderr)
        platform = "cpu"
    else:
        print(f"bench: backend '{platform}' is up "
              f"({time.time()-T0:.0f}s in)", file=sys.stderr)

    probe_restarts = 0
    cpu_only = False  # sticky: a probe reported plain CPU (no plugin)
    # the restart clock measures silence BEYOND the initial probe
    # window — a cold tunnel gets PROBE_S + BENCH_PROBE_RESTART_S of
    # undisturbed warming before its first restart (the keep_alive
    # design must survive the restart logic)
    t_probe_start = time.time()

    def restart_probe():
        """Kill the current probe, start a fresh one, and stamp the
        restart history into the emitted JSON — the diag must survive
        even if the final probe is still hung at emit time (the
        whole-run-wedged case is the one this exists for)."""
        nonlocal probe_restarts, t_probe_start
        global _PROBE
        _kill_proc(_PROBE)
        probe_restarts += 1
        t_probe_start = time.time()
        _PROBE = start_probe()
        _EXTRA["probe"] = probe_diag(_PROBE, None, time.time() - t_probe0)
        _EXTRA["probe"]["restarts"] = probe_restarts

    def late_probe_check():
        """Re-check the still-warming probe (called between tiers): a
        cold tunnel can come up mid-ladder, and every remaining tier
        should then run on the accelerator, not just the headline.

        A probe child whose first backend touch HUNG (tunnel wedged
        mid-session — observed r4: device calls block forever, outliving
        the client that issued them) will never exit, so polling it
        forever detects nothing even after the tunnel recovers.  After
        ``BENCH_PROBE_RESTART_S`` of silence the stuck child is killed
        and a FRESH probe starts: a recovered tunnel answers a fresh
        first-touch in seconds."""
        nonlocal force_cpu, platform, cpu_only
        if not force_cpu or cpu_only:
            return
        probe = _PROBE
        if probe.poll() is None:
            restart_s = float(os.environ.get("BENCH_PROBE_RESTART_S",
                                             "240"))
            if (time.time() - t_probe_start > restart_s
                    and _remaining() > 90):
                restart_probe()
                print(f"bench: probe hung >{restart_s:.0f}s; restarted "
                      f"(attempt {probe_restarts + 1})", file=sys.stderr)
            return
        late = finish_probe(probe, 1.0) if probe.returncode == 0 else None
        _EXTRA["probe"] = probe_diag(probe, late, time.time() - t_probe0)
        _EXTRA["probe"]["restarts"] = probe_restarts
        if late and late != "cpu":
            print(f"bench: accelerator '{late}' came up late "
                  f"({time.time()-T0:.0f}s in); unpinning remaining "
                  "tiers", file=sys.stderr)
            force_cpu = False
            platform = late
        elif late == "cpu":
            # the probe reached a backend and it is plain CPU: no
            # accelerator plugin exists on this host, so further
            # restarts can never change the outcome (and would add
            # measurement noise next to the running tiers)
            cpu_only = True
        elif probe.returncode is not None and _remaining() > 90:
            # probe child crashed (tunnel flake): keep trying — it may
            # open later in the budget
            restart_probe()

    def tier_headline(name, n_ops, n_procs, res, t_dev, comp):
        """Build the headline dict for a decided single-history tier."""
        decided = res["valid"] in (True, False)
        # a resumed search's verdict cost the CUMULATIVE device seconds
        # across every contributing run (tunnel windows + retries), not
        # this run's tail — all speedups and the headline rate use that
        # basis, and the metric string says so
        resumed = bool(res.get("resumed"))
        t_basis = ((res.get("elapsed_total") or t_dev)
                   if resumed else t_dev)
        h16 = comp.get("host16") or {}
        hlin = comp.get("host_linear") or {}
        vs16 = None
        if decided and h16.get("valid") in (True, False) and t_basis > 0:
            vs16 = round(h16["seconds"] / t_basis, 2)
        vslin = None
        if decided and hlin.get("valid") in (True, False) and t_basis > 0:
            vslin = round(hlin["seconds"] / t_basis, 2)
        # vs_baseline: measured when the portfolio had >= 8 cores
        # (BASELINE.json names a 16-core comparator); otherwise a
        # clearly-labeled extrapolation (VERDICT r3 item 4) — a
        # portfolio races *independent* legs on ONE history, so its
        # >=8-core wall-clock ~= its fastest single-core leg, which is
        # `linear` on every tier measured so far.
        vs_baseline = vs_basis = None
        if vs16 is not None and (h16.get("n_procs") or 0) >= 8:
            vs_baseline = vs16
            vs_basis = (f"measured {h16['n_procs']}-process portfolio "
                        "on this host")
        elif vslin is not None:
            vs_baseline = vslin
            vs_basis = (
                "EXTRAPOLATED: 16-core portfolio modeled as its fastest "
                "single-core leg (`linear`) — portfolio legs race "
                "independently on one history, so extra cores do not "
                "speed the winning leg; measured >=8-core portfolio "
                f"unavailable on this {cores}-cpu host")
        backend = res["backend"]
        wl = "mutex" if name.startswith("mutex") else "CAS-register"
        if decided:
            metric = (f"ops-verified/sec, {res['n_ops']}-op "
                      f"{n_procs}-proc {wl} history, decided "
                      f"verdict ({'valid' if res['valid'] else 'invalid'}"
                      f"), {backend} backend"
                      + (", cumulative over resumed runs" if resumed
                         else ""))
            value = round(res["n_ops"] / t_basis, 1)
            unit = "ops/s"
        else:
            metric = (f"configurations-explored/sec, {res['n_ops']}-op "
                      f"{n_procs}-proc {wl} history "
                      f"(UNDECIDED within deadline), {backend} backend")
            value = round(res.get("rate") or 0.0, 1)
            unit = "configs/s"
        return {
            "metric": metric, "value": value, "unit": unit,
            "vs_baseline": vs_baseline,
            "detail": {
                "vs_baseline_basis": vs_basis,
                "n_ops": res["n_ops"],
                "backend": backend,
                "engine": res.get("engine"),
                "device_verdict": res["valid"],
                "device_seconds": round(t_dev, 3),
                "device_seconds_incl_compile": round(res["t_first"], 3),
                "resumed": resumed or None,
                "device_seconds_cumulative": (round(t_basis, 3)
                                              if resumed else None),
                "backends_contributing": (res.get("backends_contributing")
                                          if resumed else None),
                "device_configs": res["configs"],
                # the failing det-depth (the obstruction's index) on an
                # invalid verdict
                "device_failing_depth": res.get("max_depth")
                if res["valid"] is False else None,
                "speedup_vs_host_linear_1core": vslin,
                "speedup_vs_host16": vs16,
                # same-engine, same-state-space hardware comparison: a
                # pinned-CPU sibling run of this tier (wide tier only —
                # cross-ENGINE rate ratios would compare different
                # config spaces and are never reported)
                "device_cpu_sibling": res.get("cpu_sibling"),
                "speedup_vs_device_cpu": res.get("speedup_vs_device_cpu"),
                # ISSUE 1 config 5: the decomposition layer's own pass
                # over this tier (cells/segments/speedup_vs_direct)
                "decomposed": res.get("decomposed"),
                "host_linear": hlin or None,
                "host16": h16 or None,
                "host_cpus": cores,
                "baseline_note": (
                    "comparators are this repo's own exact host "
                    "checkers (single-core `linear` and a "
                    f"{min(16, cores)}-process portfolio on this "
                    f"{cores}-cpu host); knossos itself cannot run in "
                    "this image — vs_baseline is null unless the "
                    "portfolio had >= 8 cores"),
            },
        }

    def record_tier(name, n_ops, n_procs, headline, res, t_dev):
        """Fold one completed tier into _BEST/_EXTRA against the
        CURRENT `host` comparators (called in-loop, and again from the
        deferred-host re-record pass)."""
        global _BEST, _BEST_PRIO, _BEST_TIER
        if name == "batch256":
            _EXTRA["batch256"] = batch_detail(res, host, t_dev)
            if _BEST is None:
                # only the batch tier completed (so far): better a batch
                # headline than the 'no tier completed' error payload
                _BEST = batch_headline(res, host, t_dev)
                _BEST_PRIO, _BEST_TIER = (0, 0, 0), name
            return
        comp = host.get(name) or {}
        tier_detail = tier_headline(name, n_ops, n_procs, res, t_dev,
                                    comp)
        agree = None
        hl = (comp.get("host_linear") or {}).get("valid")
        if res["valid"] in (True, False) and hl in (True, False):
            agree = res["valid"] == hl
        # a DECIDED verdict always outranks an undecided rate tier —
        # without this, a BENCH_TIER_ORDER subset can put the wide
        # (usually undecided) tier's configs/s over a decided headline
        prio = (1 if (headline or QUICK) else 0,
                1 if res["valid"] in (True, False) else 0, n_ops)
        if prio > _BEST_PRIO:
            # the largest completed register tier is the headline when
            # the designated headline tier never runs (quick mode,
            # BENCH_TIER_ORDER subsets, budget exhaustion)
            _BEST = tier_detail
            _BEST_PRIO, _BEST_TIER = prio, name
        if headline or QUICK:
            # the headline already carries the full detail; avoid a
            # duplicate copy in the extras
            _EXTRA[f"tier_{name}"] = {"host_agrees": agree,
                                      "see": "detail (headline tier)"}
        else:
            _EXTRA[f"tier_{name}"] = {**tier_detail["detail"],
                                      "host_agrees": agree}

    def maybe_cpu_sibling(name, res, budget, tier_s):
        """Same-engine hardware comparison for the wide tier: re-run it
        on a pinned CPU (fresh — no checkpoint, so the sibling can't
        inherit another backend's carry) and attach the rate ratio.
        The ratio is only computed for a NON-resumed device run: a
        resumed run's rate is cumulative across backends and would
        blend CPU-explored work into the accelerator's numerator."""
        if not (name == "10k64" and res["backend"] not in (None, "cpu")
                and not res.get("resumed")
                and _remaining() > host_reserve + tier_s + 60):
            # resumed runs never get the ratio (blended-backend rate),
            # so don't spend ~20% of the budget on a sibling whose
            # comparison would be suppressed anyway
            return
        sib = run_tier(name, budget, tier_s, force_cpu=True,
                       timeout=min(_remaining() - host_reserve - 30,
                                   tier_s * 1.5 + 60), ckpt=False)
        if not sib:
            return
        res["cpu_sibling"] = {k: sib.get(k)
                              for k in ("rate", "configs", "t_dev",
                                        "valid")}
        if (sib.get("rate") and res.get("rate")
                and not res.get("resumed")):
            res["speedup_vs_device_cpu"] = round(
                res["rate"] / sib["rate"], 2)
        print(f"bench: tier {name} cpu sibling rate={sib.get('rate')} "
              f"(speedup {res.get('speedup_vs_device_cpu')})",
              file=sys.stderr)

    # --- device tiers: smallest first, best completed wins --------------
    ran_on_cpu_fallback: list[tuple] = []  # tier specs to re-run on a late
    #                                        accelerator arrival
    completed: list[tuple] = []  # (spec..., res, t_dev) for re-recording
    # with a deferred host phase, the device ladder must LEAVE room for
    # it: the comparators are what turn tier times into speedups, and a
    # ladder that spends _remaining() to the floor would bank a bench
    # with null vs_baseline forever
    # default = HOST_S: host_comparators spends share-of-HOST_S per tier
    # and keeps its own 120s emit slack, so a smaller reserve silently
    # undecides the 10k comparator (~52s on the r4 bench host)
    host_reserve = (float(os.environ.get("BENCH_HOST_RESERVE_S",
                                         str(HOST_S)))
                    if defer_host else 20.0)
    for name, n_ops, n_procs, budget, headline, tier_s in tiers:
        late_probe_check()
        if _remaining() < 45 + (host_reserve if defer_host else 0):
            print(f"bench: skipping tier {name} (out of budget)",
                  file=sys.stderr)
            break
        # compile slack on top of the search deadline: the adaptive
        # driver may compile several frontier widths (~20-40s each on a
        # cold TPU; near-zero with a warm .jax_cache)
        timeout = min(_remaining() - host_reserve, tier_s * 2.2 + 240)
        res = run_tier(name, budget, tier_s, force_cpu=force_cpu,
                       timeout=timeout)
        if res is None and not force_cpu:
            # accelerator child crashed or hung (worker watchdog /
            # tunnel wedge).  The wedge outlives the child and would
            # hang every later unpinned child too — pin the REST of the
            # ladder to CPU and restart the probe: if the tunnel
            # recovers, the late-probe path unpins and re-runs.
            print(f"bench: tier {name} child died; pinning remaining "
                  "tiers to CPU (probe restarted)", file=sys.stderr)
            force_cpu = True
            restart_probe()
            # the retry must leave the deferred host phase its reserve
            # too, or a wedge on the last tier starves the comparators
            # and every headline re-records with null speedups
            retry_cap = _remaining() - (host_reserve if defer_host
                                        else 15)
            if retry_cap > 45:
                res = run_tier(name, budget, tier_s, force_cpu=True,
                               timeout=min(retry_cap,
                                           tier_s * 2.2 + 60))
        if res is None:
            continue
        if res["backend"] == "cpu" and not force_cpu:
            # the child silently fell back (plugin present, chip not):
            # remember the tier so a late arrival re-runs it
            ran_on_cpu_fallback.append((name, n_ops, n_procs, budget,
                                        headline, tier_s))
        elif force_cpu:
            ran_on_cpu_fallback.append((name, n_ops, n_procs, budget,
                                        headline, tier_s))
        t_dev = res["t_dev"]
        print(f"bench: tier {name}: verdict={res['valid']} in "
              f"{t_dev:.2f}s ({res['configs']} configs) "
              f"backend={res['backend']}", file=sys.stderr)
        maybe_cpu_sibling(name, res, budget, tier_s)
        completed.append((name, n_ops, n_procs, budget, headline,
                          tier_s, res, t_dev))
        record_tier(name, n_ops, n_procs, headline, res, t_dev)

    # --- deferred host comparators --------------------------------------
    # the early probe found an open tunnel, so the device ladder ran
    # first; now pay the host phase and re-record every tier headline
    # against the fresh comparator numbers
    if defer_host:
        host.update(host_comparators(tiers))
        cores = host.get("host_cpus", cores)
        _EXTRA["host_cpus"] = cores
        _BEST, _BEST_PRIO, _BEST_TIER = None, (-1, -1, -1), None
        for (name, n_ops, n_procs, budget, headline, tier_s,
             res, t_dev) in completed:
            record_tier(name, n_ops, n_procs, headline, res, t_dev)

    # --- late-probe second chance --------------------------------------
    # a cold tunnel can outlive the probe budget but come up during the
    # CPU ladder: if it has by now (and reports a non-cpu platform),
    # re-run every tier that fell back to CPU — headline first, then the
    # batch tier, then the rest — promoting accelerator results; this is
    # the evidence this benchmark exists to produce (VERDICT r3 item 1)
    late_probe_check()
    # redo only when an accelerator actually exists (platform flips away
    # from "cpu" only via late_probe_check / the initial probe): on a
    # genuinely CPU-only host the ladder results already stand
    if platform != "cpu" and not force_cpu and ran_on_cpu_fallback:
        redo = sorted(ran_on_cpu_fallback,
                      key=lambda t: (not t[4], t[0] != "batch256", t[1]))
        for name, n_ops, n_procs, budget, headline, tier_s in redo:
            if _remaining() < 60:
                break
            print(f"bench: re-running tier {name} on '{platform}'",
                  file=sys.stderr)
            res = run_tier(name, budget, tier_s, force_cpu=False,
                           timeout=min(_remaining() - 15,
                                       tier_s * 2.2 + 240))
            if not res or res.get("backend") in (None, "cpu"):
                continue
            t_dev = res["t_dev"]
            maybe_cpu_sibling(name, res, budget, tier_s)
            if name == "batch256":
                _EXTRA["batch256"] = batch_detail(res, host, t_dev)
                if _BEST_TIER == name:
                    _BEST = batch_headline(res, host, t_dev)
                continue
            promoted = tier_headline(name, n_ops, n_procs, res, t_dev,
                                     host.get(name) or {})
            if headline or QUICK or _BEST_TIER == name:
                cpu_best = _BEST
                _BEST = promoted
                _BEST_TIER = name
                _BEST["detail"]["cpu_fallback_headline"] = (
                    {k: cpu_best[k] for k in
                     ("metric", "value", "vs_baseline")}
                    if cpu_best else None)
            else:
                hl = (host.get(name, {}).get("host_linear") or {})
                agree = None
                if res["valid"] in (True, False) and \
                        hl.get("valid") in (True, False):
                    agree = res["valid"] == hl["valid"]
                _EXTRA[f"tier_{name}"] = {**promoted["detail"],
                                          "host_agrees": agree}

    _emit()
    _reap_procs()


if __name__ == "__main__":
    # The host-only tiers force their platform env BEFORE any jax
    # import; hoisted here because the devlint preflight below stages
    # kernels (importing jax) and would otherwise pin the platform
    # first — the shard tier in particular needs its 8-device virtual
    # mesh.  The per-branch setdefaults stay as documentation.
    if any(f in sys.argv
           for f in ("--stream-tier", "--fleet-tier", "--shard-tier")):
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--shard-tier" in sys.argv:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    # Every dispatch below can write BENCH_trace_*.json under --trace;
    # all of them go through the device-contract preflight (run-once,
    # so the main() ladder does not repeat it).
    _devlint_preflight()
    if "--dpor-probe" in sys.argv:
        # the dynamic-layer probe (ISSUE 14): device-mask / dead-value
        # dedup / dup-edge reductions over the 10k tiers ->
        # BENCH_dpor.json, spans in BENCH_trace_dpor.json
        run_dpor_probe()
    elif "--hb-probe" in sys.argv:
        # the happens-before pre-pass probe (ISSUE 12): decided-fast
        # fraction and pruned-vs-raw bounds over the 10k tiers ->
        # BENCH_hb.json, spans in BENCH_trace_hb.json
        run_hb_probe()
    elif "--stream-tier" in sys.argv:
        # the streaming tier (jepsen_tpu/stream/bench.py): time-to-
        # first-verdict, violation-detection latency, sustained
        # multiplexed ingest -> BENCH_stream.json.  Host-only (the
        # stream folds are host sweeps at this scale), so it runs
        # standalone without the device probe machinery above.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from jepsen_tpu.stream.bench import run_stream_tier

        run_stream_tier(REPO, quick=QUICK)
    elif "--fleet-tier" in sys.argv:
        # the fleet tier (jepsen_tpu/fleet/bench.py): 2 routed
        # workers behind the rendezvous router, warm-boot first, then
        # a synthetic client swarm ramp to the throughput knee ->
        # BENCH_fleet.json + BENCH_trace_fleet.json.  Host-only like
        # the stream tier; the compile spans in the trace are the
        # warm-boot evidence either way.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        from jepsen_tpu.fleet.bench import run_fleet_tier

        run_fleet_tier(REPO, quick=QUICK)
    elif "--shard-tier" in sys.argv:
        # the shard tier (jepsen_tpu/checker/shard_bench.py): the
        # bucket-then-shard scheduler vs the fused single-shape mesh
        # dispatch over a mixed-size key set -> BENCH_shard.json +
        # BENCH_trace_shard.json.  Runs on the virtual 8-device CPU
        # mesh unless real chips are attached — both env knobs must
        # land before jax imports.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        from jepsen_tpu.checker.shard_bench import run_shard_tier

        run_shard_tier(REPO, quick=QUICK)
    elif "--run-tier" in sys.argv:
        i = sys.argv.index("--run-tier")
        tier_name = sys.argv[i + 1]
        budget_arg = int(sys.argv[sys.argv.index("--budget") + 1])
        from jepsen_tpu import obs as _obs

        with _obs.span(f"tier:{tier_name}", cat="run"):
            run_tier_child(tier_name, budget_arg)
        if _obs.enabled():
            # the tier's flight recording lands next to the numbers
            _obs.write_trace(os.path.join(
                REPO, f"BENCH_trace_{tier_name}.json"))
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — always emit the JSON line
            print(f"bench: fatal {e!r}", file=sys.stderr)
            _emit()
            raise
