"""Headline benchmark: linearizability-check throughput on device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N}

The BASELINE.md north star is a 10k-op, 32-process CAS-register history
(the knossos worst case is the search, not the I/O).  The reference's
checker is knossos on a JVM sized -Xmx32g (jepsen/project.clj:25); no JVM
exists in this image, so the stand-in baseline is this repo's exact host
oracle (checker/seq.py — the same Wing-Gong/Lowe configuration search
knossos.wgl performs, with the same memoization), measured on the same
history and normalized per-configuration:

    vs_baseline = (device configs/sec) / (host-oracle configs/sec)

Both engines dedup over the identical configuration space, so configs/sec
is apples-to-apples; the history is corrupted near its end so both must
sweep the space rather than lucky-dive (DFS on a valid history can dive
straight to the goal, which measures luck, not throughput).  NOTE on
methodology: the host oracle is single-threaded Python; knossos on a
16-core JVM would be faster than it, so vs_baseline OVERSTATES the speedup
against knossos — the absolute configs/sec figures are printed so an
offline knossos comparison can be made.

Time-bounding (round-2 lesson): a full sweep of the 10k-op history needs
~10k BFS levels and the oracle's per-config cost grows with history
length (bigint masks), so NEITHER engine is asked to finish it.  Both
run the same history under wall-clock deadlines and report throughput;
the 1k tier still runs to completion so a real verdict (and agreement
with the oracle) is part of the output.  A 256-key batch tier mirrors
BASELINE config #3 (the jepsen.independent vmap axis — the TPU's
production shape).

Robustness contract (VERDICT r1 item 1): this script ALWAYS emits its
JSON line.  The TPU (axon PJRT plugin) can take minutes of wall clock on
first backend touch, hang forever when the tunnel is down, or KILL its
worker if any single execution outlives its ~60s watchdog — and a
crashed worker poisons the whole process's jax backend.  So:

  * the backend is probed in a subprocess while the host-oracle baseline
    runs in the parent;
  * every device tier runs in its OWN subprocess (``--run-tier``) with a
    parent-side timeout: a worker crash costs one tier, not the bench,
    and the parent retries the tier on a pinned-CPU child;
  * tiers run smallest-first under a wall-clock budget, and
    SIGTERM/SIGALRM print the best completed tier before exiting.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

QUICK = "--quick" in sys.argv

T0 = time.time()
# Total wall-clock budget for the whole script.  The driver's own timeout
# is unknown; stay comfortably inside a 20-minute envelope by default.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "300" if QUICK else "1100"))
# Backend probe budget: axon first touch has been observed to take ~9min
# when the tunnel is cold (and 2s when it is warm).
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "60" if QUICK else "300"))
# Oracle baseline phase cap (runs concurrently with the backend probe).
ORACLE_S = float(os.environ.get("BENCH_ORACLE_S", "45" if QUICK else "150"))
# Per-device-tier search deadline (excludes compile).
TIER_S = float(os.environ.get("BENCH_TIER_S", "60" if QUICK else "150"))

#: (name, n_ops, n_procs, device config budget, headline) — the tiers
#: mirror BASELINE.md's configs: #2-ish (1k-op register), #4 (mutex with
#: nemesis-induced :info ops; detail-only — lock serialization keeps its
#: config space tiny, so it demonstrates indeterminate-op correctness,
#: not throughput), #5 (10k-op CAS stress; the headline), #3 (the
#: 256-key independent batch)
TIERS = [("1k", 1_000, 32, 2_000_000, True),
         ("mutex2k", 2_000, 16, 20_000_000, False),
         ("10k", 10_000, 32, 200_000_000, True),
         ("batch256", 128, 8, 2_000_000, False)]

_BEST: dict | None = None
_EXTRA: dict = {}
_EMITTED = False
_PROBE: "subprocess.Popen | None" = None
_CHILD: "subprocess.Popen | None" = None


def make_seq(name: str):
    """Deterministic per-tier history (seeded by the tier name, so child
    processes rebuild the identical history)."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register, mutex
    from jepsen_tpu.synth import (corrupt_read, register_history,
                                  sim_mutex_history)

    spec = {t[0]: t for t in TIERS}[name]
    _, n_ops, n_procs, _, _ = spec
    rng = random.Random(f"bench-{name}")
    if name.startswith("mutex"):
        # BASELINE config #4: lock workload with nemesis-induced :info
        # (crashed) ops — the indeterminate-op stressor.  An acquire
        # chain is appended so the history is invalid NO MATTER how the
        # checker places the :info ops: each :info release can "unlock"
        # at most once, so (#info + 2) consecutive ok acquires cannot
        # all be explained.  (A valid history would be disposed of by
        # the O(n) greedy witness, as knossos's DFS would lucky-dive;
        # the tier must measure the sweep.)
        from jepsen_tpu.history import invoke_op, ok_op

        model = mutex()
        h = sim_mutex_history(rng, n_ops=n_ops, n_procs=n_procs,
                              crash_p=0.01, max_crashes=12)
        n_info = sum(1 for op in h if op.type == "info")
        for i in range(n_info + 2):
            p = n_procs + i
            h = h + [invoke_op(p, "acquire", None),
                     ok_op(p, "acquire", None)]
        return encode_ops(h, model.f_codes), model
    model = cas_register()
    h = register_history(rng, n_ops=n_ops, n_procs=n_procs, overlap=8,
                         crash_p=0.002, max_crashes=8, n_values=4)
    h = corrupt_read(rng, h, at=0.98)
    return encode_ops(h, model.f_codes), model


def make_batch(n_keys: int = 256):
    """BASELINE config #3: n_keys independent per-key register histories
    (the jepsen.independent shape, independent.clj:247-298), a quarter
    corrupted so they must be searched, not greedy-witnessed."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    model = cas_register()
    seqs = []
    for k in range(n_keys):
        rng = random.Random(f"bench-batch-{k}")
        h = register_history(rng, n_ops=128, n_procs=8, overlap=4,
                             crash_p=0.01, max_crashes=2, n_values=4)
        if k % 4 == 0:
            h = corrupt_read(rng, h, at=0.85)
        seqs.append(encode_ops(h, model.f_codes))
    return seqs, model


def _remaining() -> float:
    return BUDGET_S - (time.time() - T0)


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    result = _BEST or {
        "metric": "ops-verified/sec, CAS-register history",
        "value": None, "unit": "ops/s", "vs_baseline": None,
        "detail": {"error": "no tier completed within budget"},
    }
    if _EXTRA and "detail" in result:
        result["detail"].update(_EXTRA)
    _EMITTED = True
    print(json.dumps(result), flush=True)


def _reap_procs():
    for proc in (_PROBE, _CHILD):
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass


def _bail(why: str):
    print(f"bench: {why} after {time.time()-T0:.0f}s; emitting "
          "best-so-far", file=sys.stderr)
    _emit()
    _reap_procs()
    os._exit(0)


def _on_signal(signum, frame):
    _bail(f"signal {signum}")


def _install_guards():
    for _sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM,
                 signal.SIGHUP):
        try:
            signal.signal(_sig, _on_signal)
        except (OSError, ValueError):
            pass

    # Two layers of deadline enforcement: an alarm (covers pure-Python
    # blocking) and a watchdog thread (covers the main thread stuck in
    # non-interruptible C code).
    signal.alarm(max(10, int(BUDGET_S - 5)))

    import threading

    def _watchdog():
        time.sleep(max(10, BUDGET_S - 2))
        _bail("watchdog deadline")

    threading.Thread(target=_watchdog, daemon=True).start()


def start_probe() -> subprocess.Popen:
    """Warm/probe the accelerator backend in a subprocess (it may block
    for minutes; it may never return if the tunnel is down)."""
    return subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d=jax.devices()[0]; print('PLATFORM', d.platform);"
         "import jax.numpy as jnp;"
         "x=jnp.ones((128,128));(x@x).block_until_ready();print('WARM')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)


def finish_probe(proc: subprocess.Popen, timeout: float, *,
                 keep_alive: bool = False) -> str | None:
    """Wait for the probe; returns the platform name or None.

    With ``keep_alive``, a timed-out probe is left RUNNING: a cold axon
    tunnel has been observed to need ~9 minutes of first-touch, so the
    CPU ladder runs while the probe keeps warming, and the accelerator
    gets a second chance afterwards (see main's late-probe retry)."""
    try:
        out, _ = proc.communicate(timeout=max(1.0, timeout))
    except subprocess.TimeoutExpired:
        if not keep_alive:
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                pass
        return None
    if proc.returncode != 0 or not out:
        return None
    platform = None
    for line in out.splitlines():
        if line.startswith("PLATFORM "):
            platform = line.split(None, 1)[1].strip()
    return platform


# ---------------------------------------------------------------------------
# child: run one tier in this process, print one JSON line
# ---------------------------------------------------------------------------


def _child_platform_pin():
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the sitecustomize-registered TPU plugin ignores the env var
        # alone; the config pin must land before first backend touch
        # (tests/conftest.py:10-23)
        jax.config.update("jax_platforms", "cpu")
    try:
        # persistent XLA compile cache: repeated bench runs (and the
        # CPU-retry child) skip recompilation
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO, ".jax_cache"))
    except Exception:
        pass
    return jax


def run_tier_child(name: str, budget: int) -> None:
    jax = _child_platform_pin()

    from jepsen_tpu.checker import linearizable as lin

    tier_deadline = float(os.environ.get("BENCH_TIER_S", "150"))

    if name == "batch256":
        seqs, model = make_batch()
        t0 = time.perf_counter()
        results = lin.search_batch(seqs, model, budget=budget)
        t_first = t_dev = time.perf_counter() - t0
        # compile-free re-time only when the first pass left room for it
        if t_first < tier_deadline * 0.5:
            t0 = time.perf_counter()
            results = lin.search_batch(seqs, model, budget=budget)
            t_dev = time.perf_counter() - t0
        n_ops = sum(len(s) for s in seqs)
        n_valid = sum(1 for r in results if r["valid"] is True)
        n_bad = sum(1 for r in results if r["valid"] is False)
        n_unk = len(results) - n_valid - n_bad
        print(json.dumps({
            "configs": sum(r["configs"] for r in results),
            "t_dev": t_dev, "t_first": t_first,
            "valid": f"{n_valid} valid / {n_bad} invalid / "
                     f"{n_unk} unknown of {len(results)} keys",
            "engine": results[0].get("engine"),
            "n_ops": n_ops, "n_keys": len(seqs),
            "backend": jax.default_backend(),
        }), flush=True)
        return

    seq, model = make_seq(name)

    slices: list[tuple[float, int]] = []  # (wall time, cumulative configs)

    def on_slice(carry, dims):
        slices.append((time.perf_counter(), int(carry[3])))

    t0 = time.perf_counter()
    out = lin.search_opseq(seq, model, budget=budget,
                           deadline=t0 + tier_deadline, on_slice=on_slice)
    t_first = time.perf_counter() - t0
    t_dev = t_first  # compile-inclusive, as a floor
    # re-run compile-free when the first run finished well under the
    # deadline (i.e. the search completed; timing it again measures the
    # kernel, not the compile)
    if t_first < tier_deadline * 0.5:
        t0 = time.perf_counter()
        out = lin.search_opseq(seq, model, budget=budget,
                               deadline=t0 + tier_deadline)
        t_dev = time.perf_counter() - t0
        rate = out["configs"] / t_dev if t_dev > 0 else None
    else:
        # deadline-bounded run: estimate steady-state throughput from the
        # slice timeline, dropping compile-dominated outlier slices (each
        # frontier-width change recompiles once; those slices' wall time
        # is compiler, not search).  Rates telescope over CONTIGUOUS runs
        # of kept slices — a width change resets the carry to the last
        # clean pre-overflow state, so the cumulative config counter can
        # regress across an excluded slice; telescoping per segment never
        # double-counts the re-run work.
        rate = None
        if len(slices) >= 3:
            dts = [slices[i + 1][0] - slices[i][0]
                   for i in range(len(slices) - 1)]
            med = sorted(dts)[len(dts) // 2]
            tot_t = tot_c = 0.0
            seg_start = None  # index into slices of current segment head
            for i, dt in enumerate(dts):
                if dt <= 4 * med:
                    if seg_start is None:
                        seg_start = i
                else:
                    if seg_start is not None:
                        tot_t += slices[i][0] - slices[seg_start][0]
                        tot_c += slices[i][1] - slices[seg_start][1]
                    seg_start = None
            if seg_start is not None:
                tot_t += slices[-1][0] - slices[seg_start][0]
                tot_c += slices[-1][1] - slices[seg_start][1]
            if tot_t > 0 and tot_c > 0:
                rate = tot_c / tot_t
        if rate is None and t_dev > 0:
            rate = out["configs"] / t_dev
    print(json.dumps({
        "configs": out["configs"],
        "t_dev": t_dev,
        "t_first": t_first,
        "rate": rate,
        "valid": out["valid"],
        "window": out.get("window"),
        "concurrency": out.get("concurrency"),
        "engine": out.get("engine"),
        "n_ops": len(seq),
        "backend": jax.default_backend(),
    }), flush=True)


def run_tier(name: str, budget: int, *, force_cpu: bool,
             timeout: float) -> dict | None:
    """Spawn a tier child; returns its parsed JSON or None."""
    global _CHILD
    env = dict(os.environ)
    env["BENCH_TIER_S"] = str(TIER_S)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    proc = _CHILD = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--run-tier", name, "--budget", str(budget)],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env)
    try:
        out, _ = proc.communicate(timeout=max(5.0, timeout))
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        print(f"bench: tier {name} child timed out ({timeout:.0f}s)",
              file=sys.stderr)
        return None
    if proc.returncode != 0 or not out.strip():
        print(f"bench: tier {name} child failed rc={proc.returncode}",
              file=sys.stderr)
        return None
    try:
        return json.loads(out.strip().splitlines()[-1])
    except json.JSONDecodeError:
        return None


def main():
    global _BEST, _PROBE

    _install_guards()
    probe = _PROBE = start_probe()

    from jepsen_tpu.checker import seq as oracle

    tiers = TIERS[:1] if QUICK else TIERS

    # Oracle baselines per tier history, time-bounded (runs while the
    # backend probe warms the tunnel in the subprocess).  Per-history
    # rates matter: the oracle's per-config cost grows with history
    # length (bigint masks), so each tier compares against the oracle ON
    # ITS OWN history.
    oracle_rates: dict[str, tuple[float, dict, float]] = {}
    for name, _n_ops, _n_procs, _b, _headline in tiers:
        if name.startswith("batch"):
            continue
        seq_t, model = make_seq(name)
        share = ORACLE_S / max(1, len(tiers) - 1)
        t0 = time.perf_counter()
        ref = oracle.check_opseq(
            seq_t, model, max_configs=100_000_000,
            deadline=t0 + max(10.0, min(share, _remaining() - 60)))
        t_ref = time.perf_counter() - t0
        rate = ref["configs"] / t_ref if t_ref > 0 else float("inf")
        oracle_rates[name] = (rate, ref, t_ref)
        print(f"bench: oracle[{name}] {ref['configs']} configs in "
              f"{t_ref:.1f}s ({rate:,.0f}/s) verdict={ref['valid']}",
              file=sys.stderr)

    # Oracle on the batch tier (each key is small; the whole batch is the
    # reference's bounded-pmap shape, run serially here).
    t_ref_batch = ref_batch_configs = None
    if not QUICK:
        seqs, _m = make_batch()
        bdl = time.perf_counter() + min(ORACLE_S, max(10.0, _remaining()-60))
        t0 = time.perf_counter()
        ref_batch_configs = 0
        done = 0
        for s in seqs:
            r = oracle.check_opseq(s, _m, deadline=bdl)
            ref_batch_configs += r["configs"]
            done += 1
            if time.perf_counter() > bdl:
                break
        t_ref_batch = time.perf_counter() - t0
        print(f"bench: oracle batch {done}/{len(seqs)} keys, "
              f"{ref_batch_configs} configs in {t_ref_batch:.1f}s",
              file=sys.stderr)
        _EXTRA["oracle_batch"] = {
            "keys_done": done, "n_keys": len(seqs),
            "seconds": round(t_ref_batch, 3),
            "configs": ref_batch_configs}

    # --- bring up the backend ------------------------------------------
    platform = finish_probe(probe, min(PROBE_S, _remaining() - 60),
                            keep_alive=True)
    force_cpu = platform is None
    if force_cpu:
        print("bench: accelerator unreachable within probe budget; "
              "forcing CPU backend (probe left warming for a late "
              "retry)", file=sys.stderr)
        platform = "cpu"
    else:
        print(f"bench: backend '{platform}' is up "
              f"({time.time()-T0:.0f}s in)", file=sys.stderr)

    # --- device tiers: smallest first, best completed wins --------------
    for name, n_ops, n_procs, budget, headline in tiers:
        if _remaining() < 45:
            print(f"bench: skipping tier {name} (out of budget)",
                  file=sys.stderr)
            break
        # compile slack on top of the search deadline: the adaptive
        # driver may compile several frontier widths (~20-40s each on a
        # cold TPU; near-zero with a warm .jax_cache)
        timeout = min(_remaining() - 20, TIER_S * 2.5 + 240)
        res = run_tier(name, budget, force_cpu=force_cpu, timeout=timeout)
        if res is None and not force_cpu:
            # accelerator child crashed (worker watchdog / tunnel): the
            # tier retries on a pinned-CPU child, isolated from the wreck
            print(f"bench: tier {name} retrying on CPU", file=sys.stderr)
            if _remaining() > 45:
                res = run_tier(name, budget, force_cpu=True,
                               timeout=min(_remaining() - 15,
                                           TIER_S * 2.5 + 60))
        if res is None:
            continue
        t_dev = res["t_dev"]
        dev_rate = res.get("rate") or (
            res["configs"] / t_dev if t_dev > 0 else float("inf"))
        print(f"bench: tier {name}: {res['configs']} configs in "
              f"{t_dev:.2f}s ({dev_rate:,.0f}/s), verdict={res['valid']} "
              f"backend={res['backend']}", file=sys.stderr)
        if name == "batch256":
            # oracle may have hit its deadline after `done` of n keys:
            # extrapolate its full-batch time before comparing equal work
            speedup = None
            ob = _EXTRA.get("oracle_batch")
            if t_ref_batch and ob and ob["keys_done"] and t_dev > 0:
                t_ref_full = t_ref_batch * ob["n_keys"] / ob["keys_done"]
                speedup = round(t_ref_full / t_dev, 2)
            _EXTRA["batch256"] = {
                **{k: res[k] for k in ("configs", "valid", "engine",
                                       "n_keys", "backend")},
                "device_seconds": round(t_dev, 3),
                "device_seconds_incl_compile": round(res["t_first"], 3),
                "keys_per_sec": round(res["n_keys"] / t_dev, 1),
                "speedup_vs_oracle_extrapolated": speedup,
            }
            if _BEST is None:
                # only the batch tier completed: better a batch headline
                # than the 'no tier completed' error payload
                _BEST = {
                    "metric": "independent-key histories checked/sec, "
                              "256-key batch (128-op, 8-proc each; 1/4 "
                              "corrupted)",
                    "value": round(res["n_keys"] / t_dev, 1),
                    "unit": "keys/s",
                    "vs_baseline": speedup,
                    "detail": {"backend": res["backend"]},
                }
            continue
        ref_rate, ref, t_ref = oracle_rates.get(
            name, (None, {"configs": 0, "valid": None}, 0.0))
        vs = round(dev_rate / ref_rate, 2) if ref_rate else None
        _EXTRA[f"tier_{name}"] = {
            "configs": res["configs"], "valid": res["valid"],
            # None (no comparison) when the oracle hit its deadline —
            # 'unknown' is not a disagreement
            "oracle_verdict_agrees":
                (res["valid"] == ref.get("valid"))
                if ref.get("valid") in (True, False) else None,
            "device_seconds": round(t_dev, 3),
            "configs_per_sec": round(dev_rate, 1),
            "vs_oracle_same_history": vs,
            "backend": res["backend"], "engine": res.get("engine"),
        }
        if not headline:
            continue
        _BEST = {
            "metric": f"configurations-explored/sec, {name}-op "
                      f"{n_procs}-proc CAS-register history (invalid "
                      "tail; deadline-bounded state-space sweep)",
            "value": round(dev_rate, 1),
            "unit": "configs/s",
            "vs_baseline": vs,
            "detail": {
                "n_ops": res["n_ops"],
                "backend": res["backend"],
                "device_seconds": round(t_dev, 3),
                "device_seconds_incl_compile": round(res["t_first"], 3),
                "device_configs": res["configs"],
                "device_verdict": res["valid"],
                "device_configs_per_sec": round(dev_rate, 1),
                "oracle_history": name,
                "oracle_seconds": round(t_ref, 3),
                "oracle_configs": ref["configs"],
                "oracle_verdict": ref["valid"],
                "oracle_configs_per_sec":
                    round(ref_rate, 1) if ref_rate else None,
                "window": res.get("window"),
                "concurrency": res.get("concurrency"),
                "engine": res.get("engine"),
                "baseline_note": "oracle is this repo's single-threaded "
                                 "exact WGL host checker, not knossos on "
                                 "16 cores; vs_baseline overstates the "
                                 "speedup vs knossos — compare absolute "
                                 "configs/sec offline",
            },
        }

    # --- late-probe second chance --------------------------------------
    # a cold tunnel can outlive the probe budget but come up during the
    # CPU ladder: if it has by now (and reports a non-cpu platform),
    # re-run the headline tier on the accelerator and promote that
    # result — it is the evidence this benchmark exists to produce
    late_platform = None
    if force_cpu and probe.poll() is not None and probe.returncode == 0:
        late_platform = finish_probe(probe, 1.0)
    if late_platform and late_platform != "cpu" \
            and _remaining() > TIER_S + 120:
        print(f"bench: accelerator '{late_platform}' came up late; "
              "re-running the headline tier unpinned", file=sys.stderr)
        for name, n_ops, n_procs, budget, headline in reversed(tiers):
            if not headline:
                continue
            res = run_tier(name, budget, force_cpu=False,
                           timeout=min(_remaining() - 15,
                                       TIER_S * 2.5 + 240))
            if res and res.get("backend") not in (None, "cpu"):
                t_dev = res["t_dev"]
                dev_rate = res.get("rate") or (
                    res["configs"] / t_dev if t_dev > 0 else float("inf"))
                ref_rate, ref, t_ref = oracle_rates.get(
                    name, (None, {"configs": 0, "valid": None}, 0.0))
                vs = round(dev_rate / ref_rate, 2) if ref_rate else None
                accel = {
                    "configs": res["configs"], "valid": res["valid"],
                    "device_seconds": round(t_dev, 3),
                    "configs_per_sec": round(dev_rate, 1),
                    "vs_oracle_same_history": vs,
                    "backend": res["backend"],
                }
                _EXTRA[f"tier_{name}_accel"] = accel
                cpu_best = _BEST
                _BEST = {
                    "metric": f"configurations-explored/sec, {name}-op "
                              f"{n_procs}-proc CAS-register history "
                              "(invalid tail; deadline-bounded "
                              "state-space sweep; late accelerator "
                              "run)",
                    "value": round(dev_rate, 1),
                    "unit": "configs/s",
                    "vs_baseline": vs,
                    "detail": {
                        **accel,
                        "cpu_fallback_headline":
                            {k: cpu_best[k] for k in
                             ("metric", "value", "vs_baseline")}
                            if cpu_best else None,
                    },
                }
            break

    _emit()
    _reap_procs()


if __name__ == "__main__":
    if "--run-tier" in sys.argv:
        i = sys.argv.index("--run-tier")
        tier_name = sys.argv[i + 1]
        budget_arg = int(sys.argv[sys.argv.index("--budget") + 1])
        run_tier_child(tier_name, budget_arg)
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — always emit the JSON line
            print(f"bench: fatal {e!r}", file=sys.stderr)
            _emit()
            raise
