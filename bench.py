"""Headline benchmark: linearizability-check throughput on device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N}

The BASELINE.md north star is a 10k-op, 32-process CAS-register history
(the knossos worst case is the search, not the I/O).  The reference's
checker is knossos on a JVM sized -Xmx32g (jepsen/project.clj:25); no JVM
exists in this image, so the stand-in baseline is this repo's exact host
oracle (checker/seq.py — the same Wing-Gong/Lowe configuration search
knossos.wgl performs, with the same memoization), measured on the same
history and normalized per-configuration:

    vs_baseline = (device configs/sec) / (host-oracle configs/sec)

Both engines dedup over the identical configuration space, so configs/sec
is apples-to-apples; the history is corrupted near its end so both must
sweep the space rather than lucky-dive (DFS on a valid history can dive
straight to the goal, which measures luck, not throughput).  NOTE on
methodology: the host oracle is single-threaded Python; knossos on a
16-core JVM would be faster than it, so vs_baseline OVERSTATES the speedup
against knossos — the absolute configs/sec figures are printed so an
offline knossos comparison can be made.

Robustness contract (VERDICT r1 item 1): this script ALWAYS emits its
JSON line.  The TPU (axon PJRT plugin) can take many minutes of wall
clock on first backend touch, or hang forever when the tunnel is down, so
the backend is probed in a subprocess while the host-oracle baseline runs
in parallel; benchmark tiers run smallest-first under a wall-clock budget;
and SIGTERM/SIGALRM print the best completed tier before exiting.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUICK = "--quick" in sys.argv

T0 = time.time()
# Total wall-clock budget for the whole script.  The driver's own timeout
# is unknown; stay comfortably inside a 30-minute envelope by default.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "300" if QUICK else "1500"))
# Backend probe budget: axon first touch has been observed to take ~9min.
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "60" if QUICK else "680"))

_BEST: dict | None = None
_EMITTED = False
_PROBE: "subprocess.Popen | None" = None


def _remaining() -> float:
    return BUDGET_S - (time.time() - T0)


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    result = _BEST or {
        "metric": "ops-verified/sec, CAS-register history",
        "value": None, "unit": "ops/s", "vs_baseline": None,
        "detail": {"error": "no tier completed within budget"},
    }
    _EMITTED = True
    print(json.dumps(result), flush=True)


def _reap_probe():
    if _PROBE is not None and _PROBE.poll() is None:
        try:
            _PROBE.kill()
            _PROBE.wait(timeout=5)
        except Exception:
            pass


def _bail(why: str):
    print(f"bench: {why} after {time.time()-T0:.0f}s; emitting "
          "best-so-far", file=sys.stderr)
    _emit()
    _reap_probe()
    os._exit(0)


def _on_signal(signum, frame):
    _bail(f"signal {signum}")


for _sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM, signal.SIGHUP):
    try:
        signal.signal(_sig, _on_signal)
    except (OSError, ValueError):
        pass

# Two layers of deadline enforcement: an alarm (covers pure-Python
# blocking) and a watchdog thread (covers the main thread being stuck in
# non-interruptible C code — e.g. this process's own first PJRT backend
# touch, where Python signal handlers never get to run).
signal.alarm(max(10, int(BUDGET_S - 5)))


def _watchdog():
    time.sleep(max(10, BUDGET_S - 2))
    _bail("watchdog deadline")


import threading  # noqa: E402

threading.Thread(target=_watchdog, daemon=True).start()


def start_probe() -> subprocess.Popen:
    """Warm/probe the accelerator backend in a subprocess (it may block
    for minutes; it may never return if the tunnel is down)."""
    return subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d=jax.devices()[0]; print('PLATFORM', d.platform);"
         "import jax.numpy as jnp;"
         "x=jnp.ones((128,128));(x@x).block_until_ready();print('WARM')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)


def finish_probe(proc: subprocess.Popen, timeout: float) -> str | None:
    """Wait for the probe; returns the platform name or None."""
    try:
        out, _ = proc.communicate(timeout=max(1.0, timeout))
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return None
    if proc.returncode != 0 or not out:
        return None
    platform = None
    for line in out.splitlines():
        if line.startswith("PLATFORM "):
            platform = line.split(None, 1)[1].strip()
    return platform


def main():
    global _BEST, _PROBE

    probe = _PROBE = start_probe()

    # --- host-side work that needs no jax: histories + oracle baseline ---
    from jepsen_tpu.checker import seq as oracle
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    rng = random.Random(42)
    model = cas_register()

    tiers = [  # (name, n_ops, n_procs, device budget, oracle cap)
        ("1k", 1_000, 32, 2_000_000, 200_000),
    ]
    if not QUICK:
        tiers.append(("10k", 10_000, 32, 50_000_000, 1_000_000))

    seqs = {}
    for name, n_ops, n_procs, _, _ in tiers:
        h = register_history(rng, n_ops=n_ops, n_procs=n_procs, overlap=8,
                             crash_p=0.002, max_crashes=8, n_values=4)
        h = corrupt_read(rng, h, at=0.98)
        seqs[name] = encode_ops(h, model.f_codes)

    # Oracle baseline on the largest tier's history (runs while the
    # backend probe warms the tunnel in the subprocess).
    big = tiers[-1][0]
    cap = tiers[-1][4]
    t0 = time.perf_counter()
    ref = oracle.check_opseq(seqs[big], model, max_configs=cap)
    t_ref = time.perf_counter() - t0
    ref_rate = ref["configs"] / t_ref if t_ref > 0 else float("inf")
    print(f"bench: oracle {ref['configs']} configs in {t_ref:.1f}s "
          f"({ref_rate:,.0f}/s)", file=sys.stderr)

    # --- bring up the backend ------------------------------------------
    platform = finish_probe(probe, min(PROBE_S, _remaining() - 60))
    if platform is None:
        print("bench: accelerator unreachable within probe budget; "
              "forcing CPU backend", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    else:
        print(f"bench: backend '{platform}' is up "
              f"({time.time()-T0:.0f}s in)", file=sys.stderr)
    import jax

    from jepsen_tpu.checker import linearizable as lin

    # --- tiered device ladder: smallest first, best completed wins ------
    measured_rate = None
    for name, n_ops, n_procs, budget, _ in tiers:
        seq = seqs[name]
        # compile + measure in one run first (counts against budget),
        # then re-run timed if time allows.
        if _remaining() < 30:
            print(f"bench: skipping tier {name} (out of budget)",
                  file=sys.stderr)
            break
        if measured_rate:
            est = budget / measured_rate + 60  # + compile slack
            if est > _remaining():
                print(f"bench: skipping tier {name} (est {est:.0f}s > "
                      f"{_remaining():.0f}s left at "
                      f"{measured_rate:,.0f} configs/s)", file=sys.stderr)
                break
        t0 = time.perf_counter()
        out = lin.search_opseq(seq, model, budget=budget)
        t_first = time.perf_counter() - t0
        t_dev = t_first  # compile-inclusive, as a floor
        if _remaining() > t_first * 1.3 + 20:
            t0 = time.perf_counter()
            out = lin.search_opseq(seq, model, budget=budget)
            t_dev = time.perf_counter() - t0
        dev_rate = out["configs"] / t_dev if t_dev > 0 else float("inf")
        measured_rate = dev_rate
        ops_per_sec = len(seq) / t_dev if t_dev > 0 else float("inf")
        print(f"bench: tier {name}: {out['configs']} configs in "
              f"{t_dev:.2f}s ({dev_rate:,.0f}/s), verdict={out['valid']}",
              file=sys.stderr)
        _BEST = {
            "metric": f"ops-verified/sec, {name}-op {n_procs}-proc "
                      "CAS-register history (invalid tail; full "
                      "state-space sweep)",
            "value": round(ops_per_sec, 1),
            "unit": "ops/s",
            "vs_baseline": round(dev_rate / ref_rate, 2) if ref_rate
            else None,
            "detail": {
                "n_ops": len(seq),
                "backend": platform,
                "device_seconds": round(t_dev, 3),
                "device_seconds_incl_compile": round(t_first, 3),
                "device_configs": out["configs"],
                "device_verdict": out["valid"],
                "device_configs_per_sec": round(dev_rate, 1),
                "oracle_history": big,
                "oracle_seconds": round(t_ref, 3),
                "oracle_configs": ref["configs"],
                "oracle_verdict": ref["valid"],
                "oracle_configs_per_sec": round(ref_rate, 1),
                "window": out.get("window"),
                "concurrency": out.get("concurrency"),
                "engine": out.get("engine"),
                "baseline_note": "oracle is this repo's single-threaded "
                                 "exact WGL host checker, not knossos on "
                                 "16 cores; vs_baseline overstates the "
                                 "speedup vs knossos — compare absolute "
                                 "configs/sec offline",
            },
        }

    _emit()


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — always emit the JSON line
        print(f"bench: fatal {e!r}", file=sys.stderr)
        _emit()
        raise
