"""Headline benchmark: linearizability-check throughput on device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N}

The BASELINE.md north star is a 10k-op, 32-process CAS-register history
(the knossos worst case is the search, not the I/O).  The reference's
checker is knossos on a JVM sized -Xmx32g (jepsen/project.clj:25); no JVM
exists in this image, so the stand-in baseline is this repo's exact host
oracle (checker/seq.py — the same Wing-Gong/Lowe configuration search
knossos.wgl performs, with the same memoization), measured on the same
history and normalized per-configuration:

    vs_baseline = (device configs/sec) / (host-oracle configs/sec)

Both engines dedup over the identical configuration space, so configs/sec
is apples-to-apples; the history is corrupted near its end so both must
sweep the space rather than lucky-dive (DFS on a valid history can dive
straight to the goal, which measures luck, not throughput).
"""

import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUICK = "--quick" in sys.argv


def ensure_live_backend(probe_timeout: int = 90) -> None:
    """The TPU is reached through a tunnel that can be down; probing it
    in-process hangs jax backend init forever.  Probe via a subprocess
    with a timeout and force the CPU backend if the accelerator is
    unreachable, so bench always produces its JSON line."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=probe_timeout)
        platform = out.stdout.strip().splitlines()[-1] if out.stdout else ""
        if out.returncode == 0 and platform:
            return  # backend comes up fine; use it as-is
    except subprocess.TimeoutExpired:
        pass
    print("accelerator unreachable; falling back to CPU", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def main():
    ensure_live_backend()
    from jepsen_tpu.checker import linearizable as lin
    from jepsen_tpu.checker import seq as oracle
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    rng = random.Random(42)
    n_ops = 1_000 if QUICK else 10_000
    model = cas_register()
    h = register_history(rng, n_ops=n_ops, n_procs=32, overlap=8,
                         crash_p=0.002, max_crashes=8, n_values=4)
    h = corrupt_read(rng, h, at=0.98)
    seq = encode_ops(h, model.f_codes)

    # --- device search (first run compiles; second run is timed) ----------
    budget = 2_000_000 if QUICK else 50_000_000
    out = lin.search_opseq(seq, model, budget=budget)
    t0 = time.perf_counter()
    out = lin.search_opseq(seq, model, budget=budget)
    t_dev = time.perf_counter() - t0
    dev_rate = out["configs"] / t_dev if t_dev > 0 else float("inf")

    # --- host-oracle baseline (capped; throughput extrapolates) -----------
    cap = 200_000 if QUICK else 1_000_000
    t0 = time.perf_counter()
    ref = oracle.check_opseq(seq, model, max_configs=cap)
    t_ref = time.perf_counter() - t0
    ref_rate = ref["configs"] / t_ref if t_ref > 0 else float("inf")

    ops_per_sec = len(seq) / t_dev if t_dev > 0 else float("inf")
    result = {
        "metric": "ops-verified/sec, 10k-op 32-proc CAS-register history "
                  "(invalid tail; full state-space sweep)",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(dev_rate / ref_rate, 2) if ref_rate else None,
        "detail": {
            "n_ops": len(seq),
            "device_seconds": round(t_dev, 3),
            "device_configs": out["configs"],
            "device_verdict": out["valid"],
            "device_configs_per_sec": round(dev_rate, 1),
            "oracle_seconds": round(t_ref, 3),
            "oracle_configs": ref["configs"],
            "oracle_verdict": ref["valid"],
            "oracle_configs_per_sec": round(ref_rate, 1),
            "window": out.get("window"),
            "concurrency": out.get("concurrency"),
            "backend": None,
        },
    }
    import jax
    result["detail"]["backend"] = jax.devices()[0].platform
    print(json.dumps(result))


if __name__ == "__main__":
    main()
