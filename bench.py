"""Headline benchmark: linearizability-check throughput on device.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "ops/s", "vs_baseline": N}

The BASELINE.md north star is a 10k-op, 32-process CAS-register history
(the knossos worst case is the search, not the I/O).  The reference's
checker is knossos on a JVM sized -Xmx32g (jepsen/project.clj:25); no JVM
exists in this image, so the stand-in baseline is this repo's exact host
oracle (checker/seq.py — the same Wing-Gong/Lowe configuration search
knossos.wgl performs, with the same memoization), measured on the same
history and normalized per-configuration:

    vs_baseline = (device configs/sec) / (host-oracle configs/sec)

Both engines dedup over the identical configuration space, so configs/sec
is apples-to-apples; the history is corrupted near its end so both must
sweep the space rather than lucky-dive (DFS on a valid history can dive
straight to the goal, which measures luck, not throughput).  NOTE on
methodology: the host oracle is single-threaded Python; knossos on a
16-core JVM would be faster than it, so vs_baseline OVERSTATES the speedup
against knossos — the absolute configs/sec figures are printed so an
offline knossos comparison can be made.

Robustness contract (VERDICT r1 item 1): this script ALWAYS emits its
JSON line.  The TPU (axon PJRT plugin) can take minutes of wall clock on
first backend touch, hang forever when the tunnel is down, or KILL its
worker if any single execution outlives its ~60s watchdog — and a
crashed worker poisons the whole process's jax backend.  So:

  * the backend is probed in a subprocess while the host-oracle baseline
    runs in the parent;
  * every device tier runs in its OWN subprocess (``--run-tier``) with a
    parent-side timeout: a worker crash costs one tier, not the bench,
    and the parent retries the tier on a pinned-CPU child;
  * tiers run smallest-first under a wall-clock budget, and
    SIGTERM/SIGALRM print the best completed tier before exiting.
"""

import json
import os
import random
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

QUICK = "--quick" in sys.argv

T0 = time.time()
# Total wall-clock budget for the whole script.  The driver's own timeout
# is unknown; stay comfortably inside a 30-minute envelope by default.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "300" if QUICK else "1500"))
# Backend probe budget: axon first touch has been observed to take ~9min.
PROBE_S = float(os.environ.get("BENCH_PROBE_S", "60" if QUICK else "420"))

#: (name, n_ops, n_procs, device budget, oracle cap)
TIERS = [("1k", 1_000, 32, 2_000_000, 200_000),
         ("10k", 10_000, 32, 50_000_000, 1_000_000)]

_BEST: dict | None = None
_EMITTED = False
_PROBE: "subprocess.Popen | None" = None
_CHILD: "subprocess.Popen | None" = None


def make_seq(name: str):
    """Deterministic per-tier history (seeded by the tier name, so child
    processes rebuild the identical history)."""
    from jepsen_tpu.history import encode_ops
    from jepsen_tpu.models import cas_register
    from jepsen_tpu.synth import corrupt_read, register_history

    spec = {t[0]: t for t in TIERS}[name]
    _, n_ops, n_procs, _, _ = spec
    rng = random.Random(f"bench-{name}")
    model = cas_register()
    h = register_history(rng, n_ops=n_ops, n_procs=n_procs, overlap=8,
                         crash_p=0.002, max_crashes=8, n_values=4)
    h = corrupt_read(rng, h, at=0.98)
    return encode_ops(h, model.f_codes), model


def _remaining() -> float:
    return BUDGET_S - (time.time() - T0)


def _emit():
    global _EMITTED
    if _EMITTED:
        return
    result = _BEST or {
        "metric": "ops-verified/sec, CAS-register history",
        "value": None, "unit": "ops/s", "vs_baseline": None,
        "detail": {"error": "no tier completed within budget"},
    }
    _EMITTED = True
    print(json.dumps(result), flush=True)


def _reap_probe():
    for proc in (_PROBE, _CHILD):
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass


def _bail(why: str):
    print(f"bench: {why} after {time.time()-T0:.0f}s; emitting "
          "best-so-far", file=sys.stderr)
    _emit()
    _reap_probe()
    os._exit(0)


def _on_signal(signum, frame):
    _bail(f"signal {signum}")


def _install_guards():
    for _sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM,
                 signal.SIGHUP):
        try:
            signal.signal(_sig, _on_signal)
        except (OSError, ValueError):
            pass

    # Two layers of deadline enforcement: an alarm (covers pure-Python
    # blocking) and a watchdog thread (covers the main thread stuck in
    # non-interruptible C code).
    signal.alarm(max(10, int(BUDGET_S - 5)))

    import threading

    def _watchdog():
        time.sleep(max(10, BUDGET_S - 2))
        _bail("watchdog deadline")

    threading.Thread(target=_watchdog, daemon=True).start()


def start_probe() -> subprocess.Popen:
    """Warm/probe the accelerator backend in a subprocess (it may block
    for minutes; it may never return if the tunnel is down)."""
    return subprocess.Popen(
        [sys.executable, "-c",
         "import jax; d=jax.devices()[0]; print('PLATFORM', d.platform);"
         "import jax.numpy as jnp;"
         "x=jnp.ones((128,128));(x@x).block_until_ready();print('WARM')"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)


def finish_probe(proc: subprocess.Popen, timeout: float) -> str | None:
    """Wait for the probe; returns the platform name or None."""
    try:
        out, _ = proc.communicate(timeout=max(1.0, timeout))
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        return None
    if proc.returncode != 0 or not out:
        return None
    platform = None
    for line in out.splitlines():
        if line.startswith("PLATFORM "):
            platform = line.split(None, 1)[1].strip()
    return platform


# ---------------------------------------------------------------------------
# child: run one tier in this process, print one JSON line
# ---------------------------------------------------------------------------


def run_tier_child(name: str, budget: int) -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the sitecustomize-registered TPU plugin ignores the env var
        # alone; the config pin must land before first backend touch
        # (tests/conftest.py:10-23)
        jax.config.update("jax_platforms", "cpu")

    from jepsen_tpu.checker import linearizable as lin

    seq, model = make_seq(name)

    deadline = T0 + float(os.environ.get("BENCH_CHILD_S", "1e9"))
    t0 = time.perf_counter()
    out = lin.search_opseq(seq, model, budget=budget)
    t_first = time.perf_counter() - t0
    t_dev = t_first  # compile-inclusive, as a floor
    # re-run compile-free only when it fits the parent's window
    if time.time() + t_first * 1.3 + 20 < deadline:
        t0 = time.perf_counter()
        out = lin.search_opseq(seq, model, budget=budget)
        t_dev = time.perf_counter() - t0
    print(json.dumps({
        "configs": out["configs"],
        "t_dev": t_dev,
        "t_first": t_first,
        "valid": out["valid"],
        "window": out.get("window"),
        "concurrency": out.get("concurrency"),
        "engine": out.get("engine"),
        "n_ops": len(seq),
        "backend": jax.default_backend(),
    }), flush=True)


def run_tier(name: str, budget: int, *, force_cpu: bool,
             timeout: float) -> dict | None:
    """Spawn a tier child; returns its parsed JSON or None."""
    global _CHILD
    env = dict(os.environ)
    env["BENCH_CHILD_S"] = str(max(5.0, timeout))
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    proc = _CHILD = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__),
         "--run-tier", name, "--budget", str(budget)],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, env=env)
    try:
        out, _ = proc.communicate(timeout=max(5.0, timeout))
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass
        print(f"bench: tier {name} child timed out ({timeout:.0f}s)",
              file=sys.stderr)
        return None
    if proc.returncode != 0 or not out.strip():
        print(f"bench: tier {name} child failed rc={proc.returncode}",
              file=sys.stderr)
        return None
    try:
        return json.loads(out.strip().splitlines()[-1])
    except json.JSONDecodeError:
        return None


def main():
    global _BEST, _PROBE

    _install_guards()
    probe = _PROBE = start_probe()

    from jepsen_tpu.checker import seq as oracle

    tiers = TIERS[:1] if QUICK else TIERS

    # Oracle baseline on the largest tier's history (runs while the
    # backend probe warms the tunnel in the subprocess).
    big = tiers[-1][0]
    cap = tiers[-1][4]
    seq_big, model = make_seq(big)
    t0 = time.perf_counter()
    ref = oracle.check_opseq(seq_big, model, max_configs=cap)
    t_ref = time.perf_counter() - t0
    ref_rate = ref["configs"] / t_ref if t_ref > 0 else float("inf")
    print(f"bench: oracle {ref['configs']} configs in {t_ref:.1f}s "
          f"({ref_rate:,.0f}/s)", file=sys.stderr)

    # --- bring up the backend ------------------------------------------
    platform = finish_probe(probe, min(PROBE_S, _remaining() - 60))
    force_cpu = platform is None
    if force_cpu:
        print("bench: accelerator unreachable within probe budget; "
              "forcing CPU backend", file=sys.stderr)
        platform = "cpu"
    else:
        print(f"bench: backend '{platform}' is up "
              f"({time.time()-T0:.0f}s in)", file=sys.stderr)

    # --- tiered device ladder: smallest first, best completed wins ------
    measured_rate = None
    for name, n_ops, n_procs, budget, _ in tiers:
        if _remaining() < 45:
            print(f"bench: skipping tier {name} (out of budget)",
                  file=sys.stderr)
            break
        if measured_rate:
            est = budget / measured_rate + 60  # + compile slack
            if est > _remaining():
                print(f"bench: skipping tier {name} (est {est:.0f}s > "
                      f"{_remaining():.0f}s left at "
                      f"{measured_rate:,.0f} configs/s)", file=sys.stderr)
                break
        timeout = _remaining() - 20
        res = run_tier(name, budget, force_cpu=force_cpu, timeout=timeout)
        if res is None and not force_cpu:
            # accelerator child crashed (worker watchdog / tunnel): the
            # tier retries on a pinned-CPU child, isolated from the wreck
            print(f"bench: tier {name} retrying on CPU", file=sys.stderr)
            if _remaining() > 45:
                res = run_tier(name, budget, force_cpu=True,
                               timeout=_remaining() - 15)
        if res is None:
            break
        t_dev = res["t_dev"]
        dev_rate = res["configs"] / t_dev if t_dev > 0 else float("inf")
        measured_rate = dev_rate
        ops_per_sec = res["n_ops"] / t_dev if t_dev > 0 else float("inf")
        print(f"bench: tier {name}: {res['configs']} configs in "
              f"{t_dev:.2f}s ({dev_rate:,.0f}/s), verdict={res['valid']} "
              f"backend={res['backend']}", file=sys.stderr)
        _BEST = {
            "metric": f"ops-verified/sec, {name}-op {n_procs}-proc "
                      "CAS-register history (invalid tail; full "
                      "state-space sweep)",
            "value": round(ops_per_sec, 1),
            "unit": "ops/s",
            "vs_baseline": round(dev_rate / ref_rate, 2) if ref_rate
            else None,
            "detail": {
                "n_ops": res["n_ops"],
                "backend": res["backend"],
                "device_seconds": round(t_dev, 3),
                "device_seconds_incl_compile": round(res["t_first"], 3),
                "device_configs": res["configs"],
                "device_verdict": res["valid"],
                "device_configs_per_sec": round(dev_rate, 1),
                "oracle_history": big,
                "oracle_seconds": round(t_ref, 3),
                "oracle_configs": ref["configs"],
                "oracle_verdict": ref["valid"],
                "oracle_configs_per_sec": round(ref_rate, 1),
                "window": res.get("window"),
                "concurrency": res.get("concurrency"),
                "engine": res.get("engine"),
                "baseline_note": "oracle is this repo's single-threaded "
                                 "exact WGL host checker, not knossos on "
                                 "16 cores; vs_baseline overstates the "
                                 "speedup vs knossos — compare absolute "
                                 "configs/sec offline",
            },
        }

    _emit()


if __name__ == "__main__":
    if "--run-tier" in sys.argv:
        i = sys.argv.index("--run-tier")
        tier_name = sys.argv[i + 1]
        budget_arg = int(sys.argv[sys.argv.index("--budget") + 1])
        run_tier_child(tier_name, budget_arg)
    else:
        try:
            main()
        except Exception as e:  # noqa: BLE001 — always emit the JSON line
            print(f"bench: fatal {e!r}", file=sys.stderr)
            _emit()
            raise